"""Crash-safe state lifecycle: durable checkpoints + enrollment WAL +
startup recovery + graceful drain (durability layer).

Before this module, every gallery row enrolled while serving lived only in
device/host memory: a process restart silently lost all enrollments since
the last manual ``save_model``, and the bare ``open+write`` save could
corrupt the only checkpoint mid-crash. This layer makes accepted
enrollments survive restarts:

- **CheckpointStore** — atomic, checksummed, versioned checkpoints in a
  retention-bounded directory. Each file is ``MAGIC + header(JSON, with a
  sha256 of the payload) + payload(msgpack arrays)``, written tmp + fsync
  + rename + directory fsync. ``load_latest`` scans newest -> oldest and
  falls back past corrupt/truncated files (quarantined to ``*.corrupt``,
  counted ``checkpoints_corrupt``) — a torn newest checkpoint costs the
  delta since the previous one plus the WAL, never the gallery.
- **EnrollmentWAL** — an append-only, fsync-policy-knobbed journal (on
  ``runtime.journal``'s shared ``RotatingJournal`` machinery, with
  size-rotation overridden to warn-only: acked records are never
  unlinked) of ``add()``ed embeddings/labels between checkpoints.
  Embedding bytes ride base64 with a per-record crc32; a torn tail
  (crash mid-append) is sealed at open and skipped on replay, never
  fatal. Appends are **strict**: a failed write raises, so the
  enrollment acknowledgment that follows it never lies. Default policy
  is ``always`` — an acknowledged enrollment is fsync-durable; the
  ``interval``/``never`` policies widen the documented fsync window in
  exchange for write cost.
- **StateLifecycle** — the glue: write-ahead ``append_enrollment`` (WAL
  first, then the gallery mutation, under one lock so a concurrent
  checkpoint can never snapshot rows the WAL hasn't sequenced),
  **background checkpointing** triggered by WAL row-count / age
  thresholds (built from ``ShardedGallery.snapshot()`` host mirrors on a
  worker thread — dispatch never blocks — with a single-flight guard),
  and **startup recovery**: newest valid checkpoint -> ``load_snapshot``,
  then WAL replay of records with ``seq`` beyond the checkpoint's
  recorded ``wal_seq`` (so the crash window between checkpoint-rename and
  WAL-truncate replays nothing twice).
- **graceful_shutdown** — the SIGTERM path: drain in-flight batches,
  stop (remaining queued frames are journaled as ``closed`` drops), take
  a final checkpoint, truncate the WAL, report the settled admission
  ledger.

Consistency contract (what the recovery chaos scenario asserts —
``scripts/chaos_soak.py --scenario recovery``): after ANY crash, restart
lands on a checksum-verified gallery equal to a prefix of the
acknowledged-enrollment history plus nothing else, and no enrollment whose
``append_enrollment`` returned (with the WAL at ``always``) is ever lost.

Known window, documented not hidden: a ``reload_gallery`` swap (retrain)
is durable only once the forced checkpoint that follows it lands — a
crash inside that window recovers the previous gallery plus every
acknowledged enrollment replayed onto it.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC
from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
from opencv_facerecognizer_tpu.runtime.journal import RotatingJournal
from opencv_facerecognizer_tpu.utils.serialization import (
    CheckpointCorruptError,
    atomic_write_bytes,
    fsync_directory,
)

#: checkpoint file magic — identifies the framed gallery-state format
#: (distinct from the model checkpoints ``utils.serialization`` writes).
CHECKPOINT_MAGIC = b"OCVFSTATE\n"
CHECKPOINT_FORMAT_VERSION = 1
CHECKPOINT_SUFFIX = ".ckpt"
QUARANTINE_SUFFIX = ".corrupt"

#: IVF quantizer sidecar (parallel.quantizer): DERIVED state persisted
#: next to the checkpoints, keyed by the checkpoint's ``wal_seq`` — a
#: mismatched or corrupt sidecar is ignored (retrain), never trusted.
SIDECAR_NAME = "quantizer.ivf"


class CheckpointVersionError(ValueError):
    """The checkpoint is from a NEWER format than this binary supports —
    intact, just unreadable here (a binary downgrade). Deliberately NOT
    ``CheckpointCorruptError``: classifying it as corrupt would quarantine
    and eventually retention-prune valid newer state, silently destroying
    enrollments on rollback. Scans skip past it non-destructively."""


class EmbedderVersionMismatchError(ValueError):
    """An enrollment stamped with one embedder version tried to land in a
    gallery serving another. One served shard set holds exactly one
    version (``runtime.rollout``'s fencing invariant) — mixing spaces
    row-wise would silently corrupt every published score against the
    mixed rows. Fails CLOSED before any WAL sequence is burned: the
    caller must route the enrollment through the rollout's staged
    re-embed, or wait for the cutover to land."""


def _encode_checkpoint(header: Dict[str, Any], payload: bytes) -> bytes:
    """``MAGIC + u32 header_len + header_json + sha256(header_json) +
    payload``. The raw 32-byte header digest covers the HEADER bytes —
    the payload has its own sha256 inside the header. Without it, a bit
    flip in e.g. the header's ``wal_seq`` digits would pass every check
    and silently mis-dedup WAL replay (phantom rows or acked loss)."""
    header_blob = json.dumps(header, sort_keys=True).encode("utf-8")
    return (CHECKPOINT_MAGIC
            + len(header_blob).to_bytes(4, "big")
            + header_blob
            + hashlib.sha256(header_blob).digest()
            + payload)


def _decode_checkpoint(blob: bytes, path: str) -> Tuple[Dict[str, Any], bytes]:
    """Parse + validate one checkpoint file's bytes; raises
    ``CheckpointCorruptError`` on ANY format/checksum miss — corruption
    must always land on the quarantine-and-fall-back path, never escape
    as a stray AttributeError/ValueError that crashes recovery."""
    if not blob.startswith(CHECKPOINT_MAGIC):
        raise CheckpointCorruptError(f"{path}: bad magic")
    off = len(CHECKPOINT_MAGIC)
    if len(blob) < off + 4:
        raise CheckpointCorruptError(f"{path}: truncated before header")
    hlen = int.from_bytes(blob[off:off + 4], "big")
    off += 4
    if hlen <= 0 or len(blob) < off + hlen + 32:
        raise CheckpointCorruptError(f"{path}: truncated header")
    header_blob = blob[off:off + hlen]
    header_digest = blob[off + hlen:off + hlen + 32]
    if hashlib.sha256(header_blob).digest() != header_digest:
        raise CheckpointCorruptError(f"{path}: header sha256 mismatch")
    try:
        header = json.loads(header_blob.decode("utf-8"))
        if not isinstance(header, dict):
            raise ValueError(f"header is {type(header).__name__}, not object")
        version = int(header.get("format_version", -1))
        want_bytes = int(header.get("payload_bytes", -1))
    except (UnicodeDecodeError, json.JSONDecodeError, TypeError,
            ValueError, AttributeError) as exc:
        raise CheckpointCorruptError(f"{path}: header decode failed: "
                                     f"{exc!r}") from exc
    if version > CHECKPOINT_FORMAT_VERSION:
        raise CheckpointVersionError(
            f"{path}: format v{version} is newer than supported "
            f"v{CHECKPOINT_FORMAT_VERSION} (binary downgrade?)")
    payload = blob[off + hlen + 32:]
    if want_bytes != len(payload):
        raise CheckpointCorruptError(
            f"{path}: payload truncated ({len(payload)} bytes, header says "
            f"{want_bytes})")
    digest = hashlib.sha256(payload).hexdigest()
    if digest != header.get("sha256"):
        raise CheckpointCorruptError(f"{path}: sha256 mismatch")
    return header, payload


def read_checkpoint_header(path: str) -> Dict[str, Any]:
    """Parse + validate ONE checkpoint's header without reading its
    payload: magic, header length, header-JSON and the 32-byte header
    sha256 — a few KB of reads on a file that may hold a multi-MB
    gallery. Read replicas re-anchor on the published ``wal_seq`` in this
    header on every WAL compaction, so the cheap form matters. Raises
    ``CheckpointCorruptError``/``CheckpointVersionError`` exactly like
    ``_decode_checkpoint`` (payload checks excepted)."""
    with open(path, "rb") as fh:
        prefix = fh.read(len(CHECKPOINT_MAGIC) + 4)
        if not prefix.startswith(CHECKPOINT_MAGIC) or len(prefix) < len(
                CHECKPOINT_MAGIC) + 4:
            raise CheckpointCorruptError(f"{path}: bad magic")
        hlen = int.from_bytes(prefix[len(CHECKPOINT_MAGIC):], "big")
        if hlen <= 0 or hlen > 64 << 20:
            raise CheckpointCorruptError(f"{path}: bad header length")
        header_blob = fh.read(hlen)
        header_digest = fh.read(32)
    if len(header_blob) < hlen or len(header_digest) < 32:
        raise CheckpointCorruptError(f"{path}: truncated header")
    if hashlib.sha256(header_blob).digest() != header_digest:
        raise CheckpointCorruptError(f"{path}: header sha256 mismatch")
    try:
        header = json.loads(header_blob.decode("utf-8"))
        if not isinstance(header, dict):
            raise ValueError("header is not an object")
        version = int(header.get("format_version", -1))
    except (UnicodeDecodeError, json.JSONDecodeError, TypeError,
            ValueError, AttributeError) as exc:
        raise CheckpointCorruptError(f"{path}: header decode failed: "
                                     f"{exc!r}") from exc
    if version > CHECKPOINT_FORMAT_VERSION:
        raise CheckpointVersionError(
            f"{path}: format v{version} is newer than supported "
            f"v{CHECKPOINT_FORMAT_VERSION}")
    return header


def scan_checkpoint_files(directory: str) -> List[Tuple[int, str]]:
    """(seq, path) of every installed checkpoint in ``directory``, newest
    first — the pure read-only sibling of
    ``CheckpointStore.checkpoint_files`` for consumers (read replicas,
    the offline verifier's ``--follow`` mode) that must never construct
    the writer-side store against a live directory."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        seq = CheckpointStore._seq_of(name)
        if seq is not None:
            out.append((seq, os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


class CheckpointStore:
    """Atomic, checksummed, versioned checkpoints in one directory.

    Filenames are ``ckpt-<seq:08d>.ckpt``; ``seq`` is monotonically
    increasing across restarts (scanned from the directory). Retention
    keeps the newest ``keep`` files. Corrupt files found during a load
    scan are quarantined (renamed ``*.corrupt``) so ops tooling can
    inspect them while the next scan skips the known-bad file cheaply.
    """

    def __init__(self, directory: str, keep: int = 3, metrics=None,
                 fault_injector=None):
        self.directory = str(directory)
        self.keep = max(1, int(keep))
        self.metrics = metrics
        #: chaos hook (runtime.faults): the ``storage`` boundary fires
        #: before the tmp+rename install (writes) and before each scan
        #: read (read_error) — one injector, every durable path.
        self._faults = fault_injector
        self._lock = threading.Lock()
        os.makedirs(self.directory, exist_ok=True)

    # ---- naming ----

    @staticmethod
    def _seq_of(filename: str) -> Optional[int]:
        base = os.path.basename(filename)
        if not (base.startswith("ckpt-") and base.endswith(CHECKPOINT_SUFFIX)):
            return None
        try:
            return int(base[len("ckpt-"):-len(CHECKPOINT_SUFFIX)])
        except ValueError:
            return None

    def checkpoint_files(self) -> List[Tuple[int, str]]:
        """(seq, path) of every installed checkpoint, newest first."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            seq = self._seq_of(name)
            if seq is not None:
                out.append((seq, os.path.join(self.directory, name)))
        out.sort(reverse=True)
        return out

    def next_seq(self) -> int:
        files = self.checkpoint_files()
        return (files[0][0] + 1) if files else 1

    # ---- writing ----

    def save(self, payload: bytes, meta: Dict[str, Any],
             fault: Optional[str] = None) -> str:
        """Install one checkpoint atomically; returns its path. ``fault``
        is the chaos hook's verdict (see ``FaultInjector.on_checkpoint``):
        ``torn`` persists a partial tmp then raises, ``crash`` completes
        the tmp but raises before the rename — both leave the previous
        checkpoint as the newest installed one."""
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- single-flight checkpoint writer: the store lock serializes save/load/retention and runs on the background checkpointer thread, never the serving loop
            seq = self.next_seq()
            header = {
                "format_version": CHECKPOINT_FORMAT_VERSION,
                "seq": seq,
                "created_ts": time.time(),
                "payload_bytes": len(payload),
                "sha256": hashlib.sha256(payload).hexdigest(),
                "meta": dict(meta),
            }
            blob = _encode_checkpoint(header, payload)
            path = os.path.join(self.directory,
                                f"ckpt-{seq:08d}{CHECKPOINT_SUFFIX}")
            if fault == "torn":
                # Die mid-write: a durable partial tmp, never renamed.
                with open(path + ".tmp", "wb") as fh:  # ocvf-lint: boundary=fence-ordering -- fault injection simulating the torn write atomic_write_* exists to prevent: partial tmp, never renamed, recovery must ignore it
                    fh.write(blob[:max(1, len(blob) // 2)])
                    fh.flush()
                    os.fsync(fh.fileno())
                raise InjectedCrashError("torn checkpoint write (tmp left)")
            if fault == "crash":
                # Die after the tmp completes but before the rename: the
                # checkpoint never installs.
                with open(path + ".tmp", "wb") as fh:  # ocvf-lint: boundary=fence-ordering -- fault injection: a COMPLETE tmp that dies before the rename; the durable install below still goes through atomic_write_bytes
                    fh.write(blob)
                    fh.flush()
                    os.fsync(fh.fileno())
                raise InjectedCrashError("crash before checkpoint rename")
            if self._faults is not None:
                # Storage boundary (disk stays broken, unlike the
                # process-death faults above): an injected ENOSPC/EIO
                # raises out of save() onto checkpoint_now's existing
                # counted-failure + backoff path; slow_fsync stalls the
                # background checkpointer thread, never the serving loop.
                self._faults.on_storage("checkpoint_write")
            atomic_write_bytes(path, blob)
            if self.metrics is not None:
                self.metrics.incr(mn.CHECKPOINTS_WRITTEN)
            self._prune_locked()
            return path

    def _prune_locked(self) -> None:
        """Retention: drop installed checkpoints beyond ``keep`` (oldest
        first), stale tmp files, and quarantined files beyond ``keep``.
        Removal failures are counted (``checkpoint_gc_errors``), never
        silent: a GC that stops GC-ing on a sick disk (EIO on unlink, an
        immutable file) is exactly the kind of creeping disk growth the
        pressure watermarks need to see coming."""
        for _seq, path in self.checkpoint_files()[self.keep:]:
            try:
                os.remove(path)
            except OSError:
                logging.getLogger(__name__).warning(
                    "checkpoint retention sweep could not remove %s", path)
                if self.metrics is not None:
                    self.metrics.incr(mn.CHECKPOINT_GC_ERRORS)
        try:
            names = os.listdir(self.directory)
        except OSError:
            if self.metrics is not None:
                self.metrics.incr(mn.CHECKPOINT_GC_ERRORS)
            return
        # atomic_write_bytes stages as '<name>.tmp.<pid>' (pid-unique so
        # concurrent writers can't share a staging file); fault-injection
        # paths still write bare '<name>.tmp' — sweep both shapes, or
        # crashed saves leak multi-MB orphans forever
        stale_tmp = [n for n in names if n.endswith(".tmp") or ".tmp." in n]
        quarantined = sorted(n for n in names if n.endswith(QUARANTINE_SUFFIX))
        for name in stale_tmp + quarantined[:-self.keep or None]:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:
                logging.getLogger(__name__).warning(
                    "checkpoint retention sweep could not remove %s", name)
                if self.metrics is not None:
                    self.metrics.incr(mn.CHECKPOINT_GC_ERRORS)

    # ---- reading ----

    def load_latest(self) -> Optional[Tuple[Dict[str, Any], bytes, str]]:
        """Newest valid checkpoint as ``(header, payload, path)``, or None
        when the directory holds none. Scans newest -> oldest; each
        corrupt/truncated file costs one ``checkpoints_corrupt`` count and
        a quarantine rename, then the scan falls back to the next older
        file — recovery proceeds on the best verified state available. A
        READ error (OSError) raises instead: it proves nothing about the
        bytes, and quarantining on it could demote a valid checkpoint
        whose WAL delta is already truncated."""
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- startup/supervisor recovery path: reads must see a settled file set, and nothing latency-sensitive contends here
            for _seq, path in self.checkpoint_files():
                try:
                    if self._faults is not None:
                        # read_error chaos: lands on the exact transient-
                        # read path below (raise, never quarantine).
                        self._faults.on_storage_read("checkpoint_read")
                    with open(path, "rb") as fh:
                        blob = fh.read()
                except OSError:
                    # A transient READ failure (EIO, NFS blip) proves
                    # nothing about the bytes — quarantining would
                    # permanently demote a possibly-valid newest
                    # checkpoint whose WAL delta was already truncated
                    # (silent loss). Fail the recovery loudly instead;
                    # the operator/supervisor retries.
                    logging.getLogger(__name__).exception(
                        "checkpoint read failed (NOT corruption): %s", path)
                    if self.metrics is not None:
                        self.metrics.incr(mn.CHECKPOINT_READ_ERRORS)
                    raise
                try:
                    header, payload = _decode_checkpoint(blob, path)
                    return header, payload, path
                except CheckpointVersionError as exc:
                    # Intact but newer than this binary (downgrade):
                    # skip WITHOUT quarantining — renaming it would let
                    # retention prune valid newer state.
                    logging.getLogger(__name__).warning(
                        "newer-format checkpoint skipped (NOT quarantined)"
                        ": %s", exc)
                    if self.metrics is not None:
                        self.metrics.incr(mn.CHECKPOINTS_VERSION_SKIPPED)
                except CheckpointCorruptError as exc:
                    logging.getLogger(__name__).warning(
                        "corrupt checkpoint skipped: %s", exc)
                    if self.metrics is not None:
                        self.metrics.incr(mn.CHECKPOINTS_CORRUPT)
                    self.quarantine(path)
            return None

    def quarantine(self, path: str) -> None:
        """Rename a corrupt checkpoint to ``*.corrupt`` so scans skip it
        cheaply while ops can still inspect the bytes."""
        try:
            os.replace(path, path + QUARANTINE_SUFFIX)
            fsync_directory(self.directory)
        except OSError:
            pass

    def verify(self) -> Dict[str, Any]:
        """Offline integrity sweep (``scripts/verify_checkpoint.py``):
        validates every installed checkpoint without quarantining.
        Returns {"ok": [paths], "corrupt": [(path, reason)],
        "newer_version": [(path, reason)], "unreadable": [(path,
        reason)]}. A newer-format file is intact-but-unreadable-here,
        reported separately from damage — and an UNREADABLE file
        (EACCES/EIO: the read itself failed) proves nothing about the
        bytes, so it is "cannot verify", never "corrupt": a backup job
        keying on the corrupt verdict must not condemn state a transient
        read error merely hid."""
        ok, corrupt, newer, unreadable = [], [], [], []
        for _seq, path in self.checkpoint_files():
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
            except OSError as exc:
                unreadable.append((path, str(exc)))
                continue
            try:
                _decode_checkpoint(blob, path)
                ok.append(path)
            except CheckpointVersionError as exc:
                newer.append((path, str(exc)))
            except CheckpointCorruptError as exc:
                corrupt.append((path, str(exc)))
        return {"ok": ok, "corrupt": corrupt, "newer_version": newer,
                "unreadable": unreadable}


def decode_enroll_record(record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Validate + decode one parsed WAL ``enroll`` record (base64 rows,
    crc32, shape); returns the record with ``embeddings``/``labels_np``
    attached, or None when validation fails. Pure — shared by the WAL's
    replay and the read-only offline verifier, which must never construct
    the writer class against live state."""
    try:
        raw = base64.b64decode(record["emb"], validate=True)
        if (binascii.crc32(raw) & 0xFFFFFFFF) != record["crc32"]:
            return None
        n, dim = int(record["n"]), int(record["dim"])
        emb = np.frombuffer(raw, np.float32)
        if emb.size != n * dim:
            return None
        out = dict(record)
        out["embeddings"] = emb.reshape(n, dim)
        out["labels_np"] = np.asarray(record["labels"], np.int32)
        return out
    except (KeyError, TypeError, ValueError, binascii.Error):
        return None


class EnrollmentWAL(RotatingJournal):
    """Write-ahead log of enrollments between checkpoints.

    One JSON line per ``add()``: ``{"kind": "enroll", "seq": n, "n": rows,
    "dim": d, "labels": [...], "label": int|null, "subject": str|null,
    "emb": base64(<f4 row bytes), "crc32": ...}``. Strict appends (a
    failed write raises — the acknowledgment must not lie) with the fsync
    policy knob inherited from ``RotatingJournal`` (default ``always``
    here: acknowledged == durable).

    Unlike the dead-letter journal, the WAL NEVER rotates records away:
    the base class's size-bound rotation would eventually unlink
    acknowledged enrollments whenever checkpoints persistently fail (a
    full or unwritable checkpoint directory) while appends keep
    succeeding — a silent breach of the acknowledged-==-durable promise.
    Crossing ``max_bytes`` here only logs + counts (``wal_over_bytes``);
    compaction is exclusively ``truncate_below`` after a checkpoint
    lands, so disk growth is the visible symptom and zero loss stays the
    invariant.
    """

    def __init__(self, path: str, max_bytes: int = 64 << 20,
                 metrics=None, fsync: str = "always",
                 fsync_interval_s: float = 1.0, fault_injector=None):
        # backups=0 everywhere: size rotation is disabled below, so .1..N
        # backup files can never exist — plumbing a backups knob through
        # would be dead machinery inviting someone to re-enable the
        # rotation this class deliberately forbids.
        # fault_injector reaches the base class: the ``storage`` boundary
        # fires inside every ``_append_locked`` (ENOSPC/EIO/slow_fsync on
        # the real write path); this class's own ``wal`` boundary hooks
        # stay the process-death simulation layer on top.
        super().__init__(path, max_bytes=max_bytes, backups=0,
                         metrics=metrics, fsync=fsync,
                         fsync_interval_s=fsync_interval_s,
                         fault_injector=fault_injector)
        self._warned_over_bytes = False
        self._seal_torn_tail()

    def _rotate_if_needed(self, incoming: int) -> None:
        """Deliberately NOT the base rotation (class docstring): acked
        records are never unlinked for size. One warning + a counter when
        the WAL first crosses ``max_bytes`` (checkpoints are failing or
        thresholds are mis-sized); appends keep going."""
        if self._warned_over_bytes:
            return
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming <= self.max_bytes:
            return
        self._warned_over_bytes = True
        if self.metrics is not None:
            self.metrics.incr(mn.WAL_OVER_BYTES)
        logging.getLogger(__name__).warning(
            "enrollment WAL exceeds %d bytes without a checkpoint "
            "truncating it — checkpoints failing, or thresholds too "
            "loose; records are retained (never rotated away)",
            self.max_bytes)

    def _seal_torn_tail(self) -> None:
        """A crash mid-append leaves a partial final line with no newline;
        the NEXT append would otherwise concatenate onto it and corrupt a
        brand-new acknowledged record. Seal the torn tail with a newline
        at open so it stays an isolated unparseable line (skipped on
        replay, visible to forensics) and new appends start clean."""
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- torn-tail seal runs once at open, before any appender exists; the seal must be durable before replay trusts the file
            try:
                if not os.path.exists(self.path) or not os.path.getsize(self.path):
                    return
                with open(self.path, "rb+") as fh:
                    fh.seek(-1, os.SEEK_END)
                    if fh.read(1) != b"\n":
                        fh.write(b"\n")
                        fh.flush()
                        os.fsync(fh.fileno())
                        if self.metrics is not None:
                            self.metrics.incr(mn.WAL_TORN_TAILS_SEALED)
            except OSError:
                if self.metrics is not None:
                    self.metrics.incr(mn.JOURNAL_ERRORS)

    def append_enroll(self, seq: int, embeddings: np.ndarray,
                      labels: np.ndarray, subject: Optional[str] = None,
                      label: Optional[int] = None,
                      embedder_version: int = 1,
                      registry: Optional[Dict[str, int]] = None) -> None:
        """Append one enrollment record; raises on write failure (strict)
        or injected crash. The caller acknowledges the enrollment only
        after this returns — with ``fsync="always"`` that acknowledgment
        is a durability promise. ``embedder_version`` stamps the embedding
        space the rows live in (the rollout fencing key: replay, replicas
        and the offline verifier all refuse to apply a row to a gallery
        serving a different version; pre-rollout records without the field
        read as version 1). ``registry`` stamps the remaining model-role
        versions the row was served under (``{"detector": v, "cascade":
        v}`` — the ISSUE 18 registry stamp): the offline verifier walks
        it per role, refusing a WAL whose rows span a role's versions
        without an intervening ``registry_cutover`` fence."""
        emb = np.ascontiguousarray(np.asarray(embeddings, np.float32))
        labels = np.asarray(labels, np.int32)
        if emb.ndim != 2 or emb.shape[0] != labels.shape[0]:
            raise ValueError(f"embeddings {emb.shape} / labels "
                             f"{labels.shape} mismatch")
        raw = emb.tobytes()
        record = {
            "kind": "enroll",
            "seq": int(seq),
            "ts": time.time(),
            "n": int(emb.shape[0]),
            "dim": int(emb.shape[1]),
            "labels": [int(v) for v in labels],
            "label": None if label is None else int(label),
            "subject": subject,
            "embedder_version": int(embedder_version),
            "emb": base64.b64encode(raw).decode("ascii"),
            "crc32": binascii.crc32(raw) & 0xFFFFFFFF,
        }
        if registry is not None:
            record["registry"] = {str(k): int(v)
                                  for k, v in registry.items()}
        line = json.dumps(record)
        fault = self._faults.on_wal_append() if self._faults is not None else None
        if fault == "crash":
            raise InjectedCrashError("crash before WAL append")
        if fault == "torn":
            # Persist exactly half the real encoding with no newline, then
            # die: the torn tail replay must skip.
            with self._lock:
                self._append_locked(line[:max(1, len(line) // 2)],
                                    newline=False)
            raise InjectedCrashError("torn WAL append")
        try:
            self.append_line(line, strict=True)
        except OSError:
            # Distinct from the shared journal_errors: a STRICT append
            # failing is an enrollment refused (never acknowledged) — the
            # exact signal the degraded-durability state machine counts
            # toward its flip.
            if self.metrics is not None:
                self.metrics.incr(mn.WAL_APPEND_ERRORS)
            raise
        if self.metrics is not None:
            self.metrics.incr(mn.WAL_APPENDS)
            self.metrics.incr(mn.WAL_ROWS_APPENDED, emb.shape[0])

    def append_cutover(self, seq: int, from_version: int, to_version: int,
                       rows: int, dim: int) -> None:
        """Append one embedder-cutover fence record (strict: the in-memory
        gallery swap is allowed only AFTER this fsyncs — write-ahead, like
        enrollment). The record marks the exact WAL position where the
        served embedding space changed: replay/replicas apply rows before
        it at ``from_version`` and after it at ``to_version``, and a crash
        between this append and the post-cutover checkpoint is recovered
        by completing the cutover from the durable staged shard set
        (``runtime.rollout`` — ``rows``/``dim`` are the completeness check
        against that stage)."""
        self.append_line(json.dumps({
            "kind": "cutover", "seq": int(seq),
            "from_version": int(from_version),
            "to_version": int(to_version),
            "rows": int(rows), "dim": int(dim), "ts": time.time(),
        }), strict=True)
        if self.metrics is not None:
            self.metrics.incr(mn.WAL_CUTOVER_RECORDS)

    def append_registry_cutover(self, seq: int, role: str,
                                from_version: int, to_version: int,
                                registry: Dict[str, int],
                                config: Any = None,
                                params_path: Optional[str] = None,
                                params_sha256: Optional[str] = None) -> None:
        """Append one model-registry fence record (strict: the manifest
        install and the in-memory param publish are allowed only AFTER
        this fsyncs — write-ahead, exactly like the embedder cutover).
        The record marks the WAL position where ``role``'s served version
        changed and carries the full post-swap registry stamp plus the
        candidate params' checksum, so recovery can COMPLETE a fenced
        swap whose manifest install never ran (params verify) or CLEANLY
        ABANDON it (params damaged — a ``registry_abort`` tombstone, the
        role stays at ``from_version``)."""
        self.append_line(json.dumps({
            "kind": "registry_cutover", "seq": int(seq), "role": str(role),
            "from_version": int(from_version),
            "to_version": int(to_version),
            "registry": {str(k): int(v) for k, v in registry.items()},
            "config": config, "params_path": params_path,
            "params_sha256": params_sha256, "ts": time.time(),
        }), strict=True)
        if self.metrics is not None:
            self.metrics.incr(mn.WAL_REGISTRY_RECORDS)

    def append_registry_abort(self, fence_seq: int, role: str,
                              to_version: int) -> None:
        """Tombstone a ``registry_cutover`` fence recovery ABANDONED (the
        staged candidate params were missing or damaged — the role never
        served ``to_version``): replay and the offline verifier's
        multi-role walk treat the fence as void, so rows after it stamped
        ``from_version`` are consistent, never a span violation. Strict:
        the abandonment is part of the durable version history."""
        seq = int(fence_seq)
        self.append_line(json.dumps({
            "kind": "registry_abort", "seq": seq, "role": str(role),
            "to_version": int(to_version), "ts": time.time(),
        }), strict=True)
        if self.metrics is not None:
            self.metrics.incr(mn.WAL_REGISTRY_ABORTS)

    def scan(self) -> Tuple[List[Dict[str, Any]], int]:
        """ONE parse of the whole WAL -> (surviving records oldest-first —
        decoded enrollments plus raw ``cutover`` fence records, in file
        order — and the highest seq in ANY record). The max covers
        enrolls, aborts, even crc-failed ones whose JSON still parses: the
        lifecycle seeds ``_wal_seq`` from it, NOT from surviving
        enrollments — seeding from survivors would reuse an aborted
        record's seq for the next acknowledged enrollment, and the abort
        tombstone would then silently filter the NEW record on the next
        recovery (acknowledged data loss). Single-pass so a large WAL
        (checkpoints failing — exactly the degraded case recovery serves)
        is not parsed twice per recovery."""
        records = list(self.records())
        highest = 0
        aborted = set()
        for record in records:
            seq = record.get("seq")
            if isinstance(seq, (int, float)):
                highest = max(highest, int(seq))
                if record.get("kind") == "abort":
                    aborted.add(int(seq))
        out = []
        for record in records:
            kind = record.get("kind")
            seq = record.get("seq")
            if (kind in ("cutover", "registry_cutover", "registry_abort")
                    and isinstance(seq, (int, float))):
                # Version fences (embedder cutovers, model-registry swaps)
                # and registry abandon tombstones: flow through in order
                # so replay, the tail consumers and the offline verifier
                # see exactly where each role's served version changed.
                out.append(dict(record))
                continue
            if kind != "enroll":
                continue
            if isinstance(seq, (int, float)) and int(seq) in aborted:
                continue
            decoded = decode_enroll_record(record)
            if decoded is None:
                if self.metrics is not None:
                    self.metrics.incr(mn.WAL_CORRUPT_RECORDS)
                continue
            out.append(decoded)
        return out, highest

    def max_seq(self) -> int:
        return self.scan()[1]

    def append_abort(self, seq: int) -> None:
        """Tombstone an enroll record whose gallery apply FAILED after the
        append (write-ahead means the record is already durable): replay
        must skip it — the enrolment was rolled back and never
        acknowledged, so resurrecting its rows on restart would invent
        phantom gallery entries. Best-effort (non-strict): if the
        tombstone itself cannot be written we are already in the failure
        path, and the residual risk is the same as a crash between append
        and apply — an at-least-once replay of an unacknowledged record."""
        self.append_line(json.dumps({"kind": "abort", "seq": int(seq),
                                     "ts": time.time()}), strict=False)
        if self.metrics is not None:
            self.metrics.incr(mn.WAL_ABORTS)

    def enrollments(self) -> Iterator[Dict[str, Any]]:
        """Decoded enrollment records oldest-first, with aborted sequences
        (``append_abort`` tombstones) filtered out. Torn lines are already
        skipped by ``records``; a line that parses but fails crc/base64
        validation is counted ``wal_corrupt_records`` and skipped too.
        Cutover fence records are filtered here (version-agnostic
        consumers); version-aware consumers use ``scan`` directly."""
        return iter(r for r in self.scan()[0] if r.get("kind") == "enroll")

    def truncate_below(self, seq: int) -> None:
        """Compact away records with ``seq`` <= the given sequence (they
        are covered by an installed checkpoint): the file is rewritten
        with only the surviving records and atomically swapped in.
        Correctness never depends on this running — replay dedups against
        the checkpoint's ``wal_seq`` either way; truncation only bounds
        disk."""
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- WAL compaction: appenders MUST be excluded while the file is rewritten and swapped, or acked rows could vanish; bounded by WAL size and off the serving path
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
            survivors: List[str] = []
            try:
                with open(self.path, "r", encoding="utf-8",
                          errors="replace") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                            covered = (isinstance(rec, dict)
                                       and int(rec.get("seq", 0)) <= seq)
                        except (json.JSONDecodeError, TypeError, ValueError):
                            continue  # torn/garbage remnant: drop it
                        if not covered:
                            survivors.append(line)
            except OSError:
                return
            blob = ("\n".join(survivors) + "\n") if survivors else ""
            try:
                atomic_write_bytes(self.path, blob.encode("utf-8"))
                self._warned_over_bytes = False  # compacted: re-arm
            except OSError:
                if self.metrics is not None:
                    self.metrics.incr(mn.JOURNAL_ERRORS)


class StateLifecycle:
    """Glue layer: WAL-backed enrollments, threshold-driven background
    checkpoints, and startup recovery over one ``state_dir``::

        state_dir/
          checkpoints/ckpt-00000001.ckpt   # CheckpointStore
          enroll.wal                        # EnrollmentWAL (+ .1 .. .N)

    Attach to a ``RecognizerService`` (``attach``) or bind a bare gallery
    + subject-name list (``bind``) — the chaos scenario drives the latter.
    """

    def __init__(self, state_dir: str, metrics=None, keep_checkpoints: int = 3,
                 checkpoint_wal_rows: int = 256,
                 checkpoint_every_s: float = 300.0,
                 wal_fsync: str = "always", wal_fsync_interval_s: float = 1.0,
                 wal_max_bytes: int = 64 << 20,
                 fault_injector=None, tracer=None):
        self.state_dir = str(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.metrics = metrics
        #: optional utils.tracing.Tracer: lifecycle spans for WAL appends,
        #: checkpoints and recovery (emitted AFTER the guarded sections —
        #: span emission never runs under the enroll/checkpoint locks).
        self.tracer = tracer
        self.checkpoint_wal_rows = int(checkpoint_wal_rows)
        self.checkpoint_every_s = float(checkpoint_every_s)
        self._faults = fault_injector
        #: optional runtime.resilience.DurabilityMonitor — the degraded-
        #: durability state machine (ISSUE 15). While it reports degraded,
        #: ``append_enrollment`` refuses CLOSED before burning a sequence
        #: (the ack never lies); WAL append outcomes feed it from outside
        #: the enroll lock. Attached by the monitor's constructor.
        self.durability = None
        self.store = CheckpointStore(os.path.join(self.state_dir, "checkpoints"),
                                     keep=keep_checkpoints, metrics=metrics,
                                     fault_injector=fault_injector)
        #: IVF quantizer sidecar (derived state, keyed by checkpoint
        #: wal_seq): written after each successful checkpoint when the
        #: attached gallery carries a ready quantizer; consulted by
        #: ``recover`` so startup skips the k-means retrain.
        self.sidecar_path = os.path.join(self.state_dir, SIDECAR_NAME)
        self.wal = EnrollmentWAL(os.path.join(self.state_dir, "enroll.wal"),
                                 max_bytes=wal_max_bytes,
                                 metrics=metrics, fsync=wal_fsync,
                                 fsync_interval_s=wal_fsync_interval_s,
                                 fault_injector=fault_injector)
        #: highest WAL sequence appended (or observed during recovery).
        self._wal_seq = 0
        self._rows_since_ckpt = 0
        self._last_ckpt_t = time.monotonic()
        # _enroll_lock orders WAL appends + gallery mutations against the
        # checkpoint snapshot: a record with seq <= the snapshot-time
        # _wal_seq is provably IN the snapshot (its apply ran inside the
        # lock before the snapshot took it), so replay-after-recovery can
        # dedup exactly. Never acquire the WAL's file lock first.
        self._enroll_lock = threading.Lock()
        # Single-flight guard: one background checkpoint at a time; an
        # overlapping THRESHOLD trigger is counted and skipped (the
        # thresholds re-fire), but a FORCED trigger (reload_gallery: the
        # in-flight checkpoint may have snapshotted the pre-swap gallery)
        # latches _force_pending so the next tick retries it.
        self._ckpt_lock = threading.Lock()
        self._force_pending = False
        # Failure backoff: a persistently failing save (disk full) must
        # not re-run a full snapshot+serialize on every serving-loop tick.
        self._ckpt_retry_backoff_s = 1.0
        self._ckpt_retry_at = 0.0
        self._gallery = None
        self._subject_names: Optional[list] = None
        self._service = None
        self._closed = False
        #: optional runtime.registry.ModelRegistry — the versioned model
        #: registry (ISSUE 18). When attached, enroll rows and checkpoint
        #: headers carry the full registry stamp, ``perform_registry_
        #: cutover`` fences detector/cascade swaps through the WAL, and
        #: recovery completes (or cleanly abandons) a fenced swap whose
        #: manifest install never ran.
        self.registry = None

    # ---- wiring ----

    def attach_registry(self, registry) -> None:
        """Wire the versioned model registry: rows/checkpoints stamp its
        versions from here on, and registry swaps fence through this
        lifecycle's WAL."""
        self.registry = registry

    def bind(self, gallery, subject_names: list) -> None:
        """Point the lifecycle at a bare gallery + live subject-name list
        (the list object is read at checkpoint time, not copied now)."""
        self._gallery = gallery
        self._subject_names = subject_names

    def attach(self, service) -> None:
        """Wire into a ``RecognizerService``: checkpoints read the live
        pipeline's gallery (it may be swapped by reload/CPU-fallback) and
        the service's subject names; committed gallery changes nudge the
        threshold check via the service's commit hooks."""
        self._service = service
        service.commit_hooks.append(self.maybe_checkpoint)

    def _targets(self):
        if self._service is not None:
            return (self._service.pipeline.gallery,
                    self._service.subject_names)
        if self._gallery is None:
            raise RuntimeError("StateLifecycle has no gallery: call "
                               "attach(service) or bind(gallery, names)")
        return self._gallery, self._subject_names

    @property
    def wal_seq(self) -> int:
        return self._wal_seq

    @property
    def rows_since_checkpoint(self) -> int:
        return self._rows_since_ckpt

    @staticmethod
    def _gallery_version(gallery) -> int:
        """The embedder version the attached gallery currently serves
        (pre-rollout galleries without the attribute read as 1)."""
        return int(getattr(gallery, "embedder_version", 1))

    @property
    def embedder_version(self) -> int:
        """The serving embedder version — read from the live gallery (the
        one source of truth; checkpoints and WAL rows are stamped from
        it)."""
        gallery, _names = self._targets()
        return self._gallery_version(gallery)

    def _role_stamp(self) -> Optional[Dict[str, int]]:
        """The non-embedder registry stamp for WAL rows (``{"detector":
        v, "cascade": v}``), or None when no registry is attached. The
        embedder rides its own ``embedder_version`` field — one source of
        truth per role, no duplication."""
        if self.registry is None:
            return None
        stamp = self.registry.stamp()
        stamp.pop("embedder", None)
        return stamp

    def registry_stamp(self) -> Optional[Dict[str, int]]:
        """The FULL registry stamp (every role, embedder from the live
        gallery) — what checkpoint headers and published results carry.
        None when no registry is attached."""
        if self.registry is None:
            return None
        gallery, _names = self._targets()
        stamp = self.registry.stamp()
        stamp["embedder"] = self._gallery_version(gallery)
        return stamp

    # ---- recovery ----

    def recover(self, gallery=None, subject_names: Optional[list] = None) -> Dict[str, Any]:
        """Startup recovery: install the newest verified checkpoint into
        the gallery (``load_snapshot`` — capacity/size/labels adopt the
        checkpoint's), restore subject names, then replay WAL records with
        ``seq`` beyond the checkpoint's recorded ``wal_seq`` in order.
        Runs under the enroll lock — the supervisor's mid-run durable
        restore must not interleave with a concurrent enrolment append or
        a background checkpoint's snapshot. Returns a report dict; raises
        ``ValueError`` when the checkpoint's embedding dim does not match
        the gallery (a state dir pointed at the wrong model is an operator
        error, not a fallback case)."""
        if gallery is not None:
            self.bind(gallery, subject_names if subject_names is not None
                      else [])
        gallery, names = self._targets()
        report: Dict[str, Any] = {"recovered_checkpoint": None,
                                  "checkpoint_size": 0, "replayed_records": 0,
                                  "replayed_rows": 0, "skipped_records": 0,
                                  "version_skipped_records": 0}
        with self._enroll_lock:
            # One scan covers replay AND the pending-cutover probe (a
            # dim-mismatched checkpoint is only recoverable when a durable
            # cutover to THIS binary's dim follows it).
            surviving, highest = self.wal.scan()
            base_seq, current_version, installed = (
                self._recover_checkpoint_locked(gallery, names, report,
                                                surviving))
            # Pending cutover: a ``cutover`` fence past the recovered
            # checkpoint is the crash window between the cutover append
            # and the post-cutover checkpoint — the staged shard set is
            # durable (write-ahead: stage fsyncs before the record), so
            # recovery COMPLETES the cutover instead of losing it.
            cutover = self._pending_cutover(surviving, base_seq)
            effective_base = base_seq
            if cutover is not None:
                self._complete_cutover_locked(gallery, names, cutover,
                                              report)
                current_version = int(cutover["to_version"])
                effective_base = int(cutover["seq"])
            elif not installed and report["recovered_checkpoint"] is None:
                pass  # empty dir: fresh start at the gallery's version
            if cutover is None:
                # Quantizer sidecar BEFORE WAL replay: replayed
                # enrollments then re-drive the same incremental
                # assignments the live process made against the sidecar's
                # centroids — identical derived state without a startup
                # k-means. Skipped entirely when a cutover was completed:
                # the sidecar's centroids live in the OLD embedding space.
                self._restore_quantizer_locked(gallery, base_seq, report)
            # Model-registry swaps (ISSUE 18): a ``registry_cutover``
            # fence whose manifest install never ran is the crash window
            # between the fence append and the atomic manifest write —
            # COMPLETE it when the staged candidate params verify, or
            # CLEANLY ABANDON it (tombstone + retired version number)
            # when they don't. Either way the fleet restarts serving
            # exactly one fenced version per role.
            self._settle_registry_locked(surviving, report)
            # WAL replay: acknowledged enrollments since the effective
            # anchor, fenced by embedder version — a row from another
            # version's space is NEVER applied (it can only arise from a
            # damaged fence; counted loudly, not mixed in).
            for record in surviving:
                if record.get("kind") != "enroll":
                    continue
                seq = int(record["seq"])
                if seq <= base_seq:
                    report["skipped_records"] += 1
                    if self.metrics is not None:
                        self.metrics.incr(mn.WAL_SKIPPED_RECORDS)
                    continue
                if seq <= effective_base:
                    # Covered by the completed cutover's staged set: the
                    # ROWS ride the stage (re-embedded), but the
                    # label->name map still re-grows from the record.
                    self._grow_names(names, record)
                    report["skipped_records"] += 1
                    continue
                if int(record.get("embedder_version", 1)) != current_version:
                    report["version_skipped_records"] += 1
                    if self.metrics is not None:
                        self.metrics.incr(mn.ROLLOUT_VERSION_SKIPPED_ROWS,
                                          int(record["n"]))
                    logging.getLogger(__name__).error(
                        "WAL record seq %d carries embedder version %s but "
                        "recovery landed on version %d — row NOT applied "
                        "(version fence; a mixed gallery is never served)",
                        seq, record.get("embedder_version"), current_version)
                    continue
                gallery.add(record["embeddings"], record["labels_np"])
                self._grow_names(names, record)
                report["replayed_records"] += 1
                report["replayed_rows"] += int(record["n"])
                if self.metrics is not None:
                    self.metrics.incr(mn.WAL_REPLAYED_RECORDS)
                    self.metrics.incr(mn.WAL_REPLAYED_ROWS, int(record["n"]))
            # Seed the sequence from EVERY record — aborts and corrupt-
            # but-parseable ones included (wal.scan docstring): seeding
            # from surviving enrollments alone would reuse a tombstoned
            # seq and the tombstone would filter the NEW record later.
            self._wal_seq = max(base_seq, highest)
            self._rows_since_ckpt = report["replayed_rows"]
        wait_ready = getattr(gallery, "wait_ready", None)
        if wait_ready is not None:
            wait_ready(timeout=300.0)
        self._last_ckpt_t = time.monotonic()
        if cutover is not None:
            # The completed cutover is in memory + stage only until a
            # NEW-version checkpoint lands; latch a forced checkpoint so
            # the next tick makes it durable (and truncates the fenced
            # WAL prefix).
            self._force_pending = True
        if self.metrics is not None:
            self.metrics.incr(mn.STATE_RECOVERIES)
            self.metrics.set_gauge(mn.WAL_ROWS, self._rows_since_ckpt)
        report["gallery_size"] = gallery.size
        report["embedder_version"] = current_version
        if self.registry is not None:
            report["registry"] = {**self.registry.stamp(),
                                  "embedder": current_version}
        # No (or stale) sidecar: the quantizer retrains in the background
        # (single-flight) while the exact matcher serves — startup never
        # blocks on a k-means.
        poke = getattr(gallery, "_poke_quantizer", None)
        if poke is not None:
            poke()
        if self.tracer is not None:
            self.tracer.emit(
                self.tracer.new_trace(), "recover", topic=LIFECYCLE_TOPIC,
                replayed_records=report["replayed_records"],
                replayed_rows=report["replayed_rows"],
                checkpoint=report["recovered_checkpoint"],
                gallery_size=int(gallery.size))
        return report

    def _restore_quantizer_locked(self, gallery, base_seq: int,
                                  report: Dict[str, Any]) -> None:
        """Reinstate the (derived) IVF quantizer from its sidecar when one
        exists AND its ``wal_seq`` matches the recovered checkpoint's —
        any mismatch, corruption or config drift falls back to a retrain,
        never a half-trusted shortlist (a wrong inverted list is a silent
        recall bug, the one failure mode this subsystem must not have)."""
        quantizer = getattr(gallery, "quantizer", None)
        if quantizer is None:
            return
        from opencv_facerecognizer_tpu.parallel.quantizer import (
            SidecarError, decode_sidecar,
        )

        try:
            with open(self.sidecar_path, "rb") as fh:
                blob = fh.read()
        except OSError:
            return  # no sidecar: the post-recovery poke retrains
        try:
            header, centroids, assign = decode_sidecar(blob)
        except SidecarError as exc:
            logging.getLogger(__name__).warning(
                "quantizer sidecar unreadable (%s); will retrain", exc)
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_SIDECAR_ERRORS)
            return
        nlist_drift = (not getattr(quantizer, "auto_nlist", False)
                       and int(header.get("nlist", -1)) != quantizer.nlist)
        if (int(header.get("wal_seq", -1)) != int(base_seq)
                or nlist_drift
                or int(header.get("seed", -1)) != quantizer.seed
                or int(header.get("dim", -1)) != gallery.dim
                # Derived state is version-bound: centroids trained in one
                # embedder's space shortlist garbage in another's. The
                # wal_seq key already fences most cases; this is the
                # defense-in-depth for a sidecar surviving a cutover.
                or int(header.get("embedder_version", 1))
                != self._gallery_version(gallery)):
            logging.getLogger(__name__).info(
                "quantizer sidecar stale (wal_seq %s vs checkpoint %s); "
                "will retrain", header.get("wal_seq"), base_seq)
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_SIDECAR_STALE)
            return
        if quantizer.install_from_arrays(centroids, assign):
            report["quantizer_sidecar"] = "loaded"
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_SIDECAR_LOADS)
        else:
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_SIDECAR_STALE)

    def _settle_registry_locked(self, surviving: List[Dict[str, Any]],
                                report: Dict[str, Any]) -> None:
        """Complete or cleanly abandon every fenced-but-uninstalled model
        registry swap (see ``recover``). Attaches a registry on the fly
        when the state dir carries a manifest but none was wired (the
        crash-restart harnesses construct the lifecycle bare) — a CORRUPT
        manifest raises ``RegistryStateError`` out of recovery: a writer
        must never guess which model versions it serves."""
        registry = self.registry
        if registry is None:
            from opencv_facerecognizer_tpu.runtime.registry import (
                MANIFEST_NAME, ModelRegistry,
            )

            if not os.path.exists(os.path.join(self.state_dir,
                                               MANIFEST_NAME)):
                return
            registry = ModelRegistry(self.state_dir, metrics=self.metrics)
            self.registry = registry
        from opencv_facerecognizer_tpu.runtime.registry import _file_sha256

        voided = {(r.get("role"), int(r.get("to_version", -1)))
                  for r in surviving if r.get("kind") == "registry_abort"}
        for record in surviving:
            if record.get("kind") != "registry_cutover":
                continue
            role = str(record.get("role"))
            to_version = int(record.get("to_version", -1))
            if (role, to_version) in voided:
                continue  # a previous recovery already abandoned it
            if registry.version(role) >= to_version:
                continue  # manifest install landed before the crash
            entry = {"role": role, "seq": int(record.get("seq", 0)),
                     "from_version": int(record.get("from_version", 0)),
                     "to_version": to_version}
            sha = record.get("params_sha256")
            path = record.get("params_path")
            params_ok = True
            if sha is not None:
                try:
                    params_ok = (path is not None and os.path.exists(path)
                                 and _file_sha256(path) == sha)
                except OSError:
                    params_ok = False
            if params_ok:
                registry.install(role, to_version,
                                 config=record.get("config"),
                                 params_path=path, params_sha256=sha)
                report.setdefault("completed_registry_swaps",
                                  []).append(entry)
                if self.metrics is not None:
                    self.metrics.incr(mn.REGISTRY_SWAPS_COMPLETED_RECOVERY)
                logging.getLogger(__name__).warning(
                    "completed pending registry swap %s v%d -> v%d from "
                    "the fence + staged params (the crash landed between "
                    "the fence record and the manifest install)", role,
                    entry["from_version"], to_version)
            else:
                try:
                    self.wal.append_registry_abort(entry["seq"], role,
                                                   to_version)
                except OSError:
                    logging.getLogger(__name__).exception(
                        "registry_abort tombstone append failed; the "
                        "abandonment stands (manifest never moved) but "
                        "the offline verifier will flag the dangling "
                        "fence")
                registry.retire(role, to_version)
                report.setdefault("abandoned_registry_swaps",
                                  []).append(entry)
                if self.metrics is not None:
                    self.metrics.incr(mn.REGISTRY_SWAPS_ABANDONED_RECOVERY)
                logging.getLogger(__name__).warning(
                    "ABANDONED pending registry swap %s v%d -> v%d: the "
                    "fenced candidate params are missing or damaged "
                    "(sha256 mismatch) — the role stays at v%d and "
                    "version %d is retired, never reused", role,
                    entry["from_version"], to_version,
                    entry["from_version"], to_version)

    @staticmethod
    def _pending_cutover(records: List[Dict[str, Any]],
                         base_seq: int) -> Optional[Dict[str, Any]]:
        """The NEWEST cutover fence record past the recovered checkpoint,
        or None. Newest wins: stacked cutovers (a cutover whose forced
        checkpoint failed, followed by another rollout) each stage the
        FULL row set, so completing the last one alone is exact."""
        pending = None
        for record in records:
            if (record.get("kind") == "cutover"
                    and int(record.get("seq", 0)) > base_seq):
                pending = record
        return pending

    def _complete_cutover_locked(self, gallery, names,
                                 cutover: Dict[str, Any],
                                 report: Dict[str, Any]) -> None:
        """Finish a cutover whose record is durable but whose post-cutover
        checkpoint never landed: install the staged shard set
        (``runtime.rollout``'s stage file — fsync-durable BEFORE the
        record was appended, by construction) as the whole gallery at the
        new version. A missing/short stage here can only be media damage;
        it raises (``RolloutStateError``) rather than mixing versions or
        silently dropping the acknowledged cutover."""
        from opencv_facerecognizer_tpu.runtime.rollout import load_stage

        rows = int(cutover["rows"])
        dim = int(cutover["dim"])
        to_version = int(cutover["to_version"])
        if dim != gallery.dim:
            raise ValueError(
                f"state dir {self.state_dir!r} holds a pending cutover to "
                f"dim={dim} but the gallery is dim={gallery.dim} — wrong "
                f"--state-dir (or wrong model) for completing this rollout?")
        emb, labels = load_stage(self.state_dir, to_version,
                                 expect_rows=rows, expect_dim=dim)
        capacity = max(int(gallery.capacity), rows)
        emb_full = np.zeros((capacity, dim), np.float32)
        emb_full[:rows] = emb
        lab_full = np.full((capacity,), getattr(gallery, "labels_pad", -1),
                           np.int32)
        lab_full[:rows] = labels
        val_full = np.zeros((capacity,), bool)
        val_full[:rows] = True
        gallery.load_snapshot(emb_full, lab_full, val_full, rows,
                              embedder_version=to_version)
        report["completed_cutover"] = {
            "seq": int(cutover["seq"]),
            "from_version": int(cutover.get("from_version", 0)),
            "to_version": to_version, "rows": rows,
        }
        if self.metrics is not None:
            self.metrics.incr(mn.ROLLOUT_CUTOVERS_COMPLETED_RECOVERY)
        logging.getLogger(__name__).warning(
            "completed pending embedder cutover v%s -> v%d from the staged "
            "shard set (%d rows; the crash landed between the cutover "
            "record and its checkpoint)", cutover.get("from_version"),
            to_version, rows)

    def _recover_checkpoint_locked(self, gallery, names,
                                   report: Dict[str, Any],
                                   wal_records: List[Dict[str, Any]],
                                   ) -> Tuple[int, int, bool]:
        """Install the newest checkpoint that BOTH checksum-verifies and
        payload-decodes, quarantining + falling back past any that fails
        either test (a checksum-valid payload msgpack rejects is corrupt
        all the same — stopping at it would silently discard every older
        valid checkpoint and recover WAL-only). Returns ``(wal_seq,
        embedder_version, installed)`` — ``installed`` is False when the
        newest checkpoint predates a pending dim-changing cutover (its
        rows are superseded by the staged set; only its ``wal_seq`` and
        subject names are adopted)."""
        from flax import serialization as flax_serialization

        while True:
            loaded = self.store.load_latest()
            if loaded is None:
                return 0, self._gallery_version(gallery), False
            header, payload, path = loaded
            meta = header.get("meta", {})
            dim = int(meta.get("dim", -1))
            ckpt_version = int(meta.get("embedder_version", 1))
            wal_seq = int(meta.get("wal_seq", 0))
            if dim != gallery.dim:
                pending = self._pending_cutover(wal_records, wal_seq)
                if (pending is not None
                        and int(pending.get("dim", -1)) == gallery.dim):
                    # Old-embedder checkpoint + a durable cutover to THIS
                    # binary's dim: the caller completes the cutover from
                    # the staged set — adopt only the names + anchor here.
                    if names is not None:
                        names[:] = [str(s) for s
                                    in meta.get("subject_names", [])]
                    report["recovered_checkpoint"] = path
                    report["checkpoint_superseded_by_cutover"] = True
                    return wal_seq, ckpt_version, False
                raise ValueError(
                    f"state dir {self.state_dir!r} holds dim={dim} "
                    f"checkpoints but the gallery is dim={gallery.dim} — "
                    f"wrong --state-dir for this model?")
            try:
                state = flax_serialization.msgpack_restore(payload)
                emb = np.asarray(state["emb"], np.float32)
                lab = np.asarray(state["lab"], np.int32)
                val = np.asarray(state["val"], bool)
            except Exception as exc:  # noqa: BLE001 — decode-corrupt
                logging.getLogger(__name__).warning(
                    "checkpoint %s payload decode failed (%r); falling "
                    "back to the previous checkpoint", path, exc)
                if self.metrics is not None:
                    self.metrics.incr(mn.CHECKPOINTS_CORRUPT)
                report.setdefault("payload_decode_errors", []).append(repr(exc))
                self.store.quarantine(path)
                continue
            size = int(meta.get("size", int(val.sum())))
            gallery.load_snapshot(emb, lab, val, size,
                                  embedder_version=ckpt_version)
            if names is not None:
                names[:] = [str(s) for s in meta.get("subject_names", [])]
            report["recovered_checkpoint"] = path
            report["checkpoint_size"] = size
            return wal_seq, ckpt_version, True

    @staticmethod
    def _grow_names(names: Optional[list], record: Dict[str, Any]) -> None:
        """Re-grow the subject-name list from a replayed record: the name
        lives at index ``label`` exactly as the enrolling service placed
        it (gaps get placeholders — they can only arise from a baseline
        checkpoint written without names, or a tombstoned record whose
        label slot a later enrolment reused)."""
        if names is None or record.get("label") is None:
            return
        label = int(record["label"])
        while len(names) <= label:
            names.append(f"subject_{len(names)}")
        if record.get("subject"):
            names[label] = str(record["subject"])

    # ---- write path ----

    def append_enrollment(self, embeddings: np.ndarray, labels: np.ndarray,
                          subject: Optional[str] = None,
                          label: Optional[int] = None,
                          apply_fn: Optional[Callable[[], None]] = None,
                          embedder_version: Optional[int] = None) -> int:
        """Write-ahead append + apply: the WAL record lands (fsynced per
        policy) BEFORE ``apply_fn`` mutates the gallery, both under the
        enroll lock, so (a) a crash after the append replays the rows on
        restart, and (b) a concurrent checkpoint can never capture gallery
        rows the WAL hasn't sequenced (its dedup would otherwise double-
        apply them). Returns the record's sequence number; raises when the
        append fails — the caller must NOT acknowledge the enrollment.

        ``embedder_version`` (when the caller knows which embedder
        produced these rows) is the version FENCE: a mismatch against the
        gallery's serving version raises ``EmbedderVersionMismatchError``
        inside the enroll lock, BEFORE any sequence is burned — an
        enrollment embedded by the outgoing model can never land after
        the cutover swapped the space under it. The WAL record is always
        stamped with the serving version it landed in.

        With a ``durability`` monitor attached and DEGRADED, the append
        is refused closed up front (``DurabilityDegradedError``, counted
        ``enrollments_refused_degraded``) — no sequence burned, no lock
        held, no doomed write against a disk already known broken."""
        dur = self.durability
        if dur is not None and dur.degraded:
            if self.metrics is not None:
                self.metrics.incr(mn.ENROLLMENTS_REFUSED_DEGRADED)
            from opencv_facerecognizer_tpu.runtime.resilience import (
                DurabilityDegradedError,
            )

            raise DurabilityDegradedError(
                "durability degraded: enrollment refused closed (WAL "
                "appends are failing on this state dir; serving "
                "continues, the recovery probe re-arms automatically)")
        n = int(np.asarray(labels).shape[0])
        t0 = time.monotonic()
        ok = False
        wal_exc: Optional[OSError] = None
        try:
            with self._enroll_lock:
                # Version fence, read under the SAME lock the cutover
                # mutates it under — the check and the append are atomic
                # against a concurrent swap.
                gallery, _names = self._targets()
                gver = self._gallery_version(gallery)
                if (embedder_version is not None
                        and int(embedder_version) != gver):
                    if self.metrics is not None:
                        self.metrics.incr(mn.ROLLOUT_VERSION_MISMATCHES)
                    raise EmbedderVersionMismatchError(
                        f"enrollment embedded by embedder v{embedder_version}"
                        f" refused: the gallery serves v{gver} — one shard "
                        f"set never mixes versions; re-embed through the "
                        f"rollout stage or retry against the new model")
                # Burn the sequence BEFORE attempting the append: a failed
                # strict append (fsync raised) may still have landed the
                # full record bytes — reissuing the seq to the next
                # enrollment would leave two enroll records sharing it,
                # which replay cannot tell apart (phantom rows /
                # cross-subject labels).
                seq = self._wal_seq = self._wal_seq + 1
                try:
                    self.wal.append_enroll(seq, embeddings, labels,
                                           subject=subject, label=label,
                                           embedder_version=gver,
                                           registry=self._role_stamp())
                except InjectedCrashError:
                    raise  # ocvf-lint: boundary=resource-pairing -- simulated kill: the burned seq leaks ON PURPOSE so recovery's abort/replay handling of a half-landed record is exercised; a real crash writes nothing post-mortem either
                except BaseException as exc:
                    # Best-effort tombstone for the possibly-landed record;
                    # if this fails too the residual risk is the documented
                    # at-least-once replay of an UNacknowledged record.
                    self.wal.append_abort(seq)
                    if isinstance(exc, OSError):
                        # Storage-shaped failure: feed the degraded-
                        # durability machine AFTER the lock releases
                        # (the flip publishes + spans — I/O that must
                        # never run under the enroll lock).
                        wal_exc = exc
                    raise
                if apply_fn is not None:
                    try:
                        apply_fn()
                    except BaseException:
                        # The apply failed AFTER the record became durable:
                        # the caller rolls the enrolment back and never
                        # acknowledges it, so tombstone the record — replay
                        # must not resurrect rows the live gallery never
                        # got.
                        self.wal.append_abort(seq)
                        raise
                self._rows_since_ckpt += n
            ok = True
        finally:
            if self.tracer is not None:
                # Emitted OUTSIDE the enroll lock (span emission never
                # nests inside durability locks); ok=False marks a
                # failed / rolled-back / crash-injected append — the
                # lifecycle spans that explain a later recovery.
                self.tracer.emit(self.tracer.new_trace(), "wal_append",
                                 topic=LIFECYCLE_TOPIC, t0=t0,
                                 dur=time.monotonic() - t0, rows=n, ok=ok)
            if dur is not None:
                # Outcome feed for the degraded-durability machine, also
                # outside the enroll lock (the degraded flip publishes a
                # status + emits a span). Only storage-shaped failures
                # count toward the flip; a version-fence refusal or an
                # apply_fn bug is not a disk symptom.
                if wal_exc is not None:
                    dur.note_wal_failure(wal_exc)
                elif ok:
                    dur.note_wal_success()
        if self.metrics is not None:
            self.metrics.set_gauge(mn.WAL_ROWS, self._rows_since_ckpt)
        self.maybe_checkpoint()
        return seq

    def stamped_snapshot(self):
        """(wal_seq, gallery snapshot, subject-names copy,
        embedder_version) read atomically against enrollments —
        ``ServiceSupervisor.checkpoint`` pairs its in-memory snapshot with
        the WAL sequence it covers so a crash restore can replay the
        acknowledged tail (``replay_tail``), and with the embedder version
        the rows live in so the restore re-installs rows AND version in
        one atomic publish (a snapshot straddling a cutover must never
        install old-space rows under the new version's stamp)."""
        gallery, names = self._targets()
        with self._enroll_lock:
            return (self._wal_seq, gallery.snapshot(),
                    list(names) if names is not None else None,
                    self._gallery_version(gallery))

    def replay_tail(self, from_seq: int) -> int:
        """Re-apply acknowledged WAL records with ``seq > from_seq`` to
        the live gallery; returns rows replayed. The supervisor's
        in-memory restore rolls the gallery back to a snapshot stamped
        ``from_seq`` — WITHOUT this replay, enrollments acknowledged after
        that stamp would vanish from serving, and the next background
        checkpoint (whose header claims the current ``wal_seq``) would
        truncate their WAL records: permanent loss of fsync-acknowledged
        data."""
        gallery, names = self._targets()
        rows = 0
        with self._enroll_lock:
            gver = self._gallery_version(gallery)
            surviving, _highest = self.wal.scan()
            for record in surviving:
                if record.get("kind") != "enroll":
                    continue
                if int(record["seq"]) <= from_seq:
                    continue
                if int(record.get("embedder_version", 1)) != gver:
                    # Version fence: a tail record from another embedder's
                    # space never lands on this gallery (can only arise
                    # when the restore snapshot straddles a cutover —
                    # counted loudly, never mixed in).
                    if self.metrics is not None:
                        self.metrics.incr(mn.ROLLOUT_VERSION_SKIPPED_ROWS,
                                          int(record["n"]))
                    continue
                gallery.add(record["embeddings"], record["labels_np"])
                self._grow_names(names, record)
                rows += int(record["n"])
        if rows and self.metrics is not None:
            self.metrics.incr(mn.WAL_TAIL_REPLAYED_ROWS, rows)
        return rows

    def perform_cutover(self, to_version: int,
                        build_fn: Callable[[], Tuple[np.ndarray, np.ndarray,
                                                     np.ndarray, int]]) -> int:
        """The atomic embedder cutover (``runtime.rollout`` drives this):
        under the enroll lock — so no enrollment can interleave between
        the fence and the swap, and no checkpoint can snapshot across it —

        1. ``build_fn()`` finalizes the staged shard set: it re-embeds the
           last few rows enrolled since the background stage caught up,
           appends them to the DURABLE stage file (fsync), and returns the
           full new-space arrays ``(emb_padded, lab, val, size)``;
        2. the ``cutover`` WAL fence record is appended (strict, fsynced)
           — write-ahead: from this instant a crash recovers INTO the new
           version (``_complete_cutover_locked``), never a mix;
        3. the gallery installs the new arrays + version in one atomic
           publish (``load_snapshot``), epoch-fenced so in-flight batches
           keep the old arrays they captured and the IVF quantizer is
           invalidated (exact matching serves until its background
           retrain — the derived-state lifecycle rides the swap).

        Returns the fence record's sequence. The caller MUST follow with a
        forced checkpoint (``checkpoint_now(wait=True)`` /
        ``maybe_checkpoint(force=True)``) — until it lands, recovery
        completes the cutover from the stage file, which therefore must
        not be discarded before the checkpoint succeeds. Read replicas see
        the fence in the tail and re-anchor on that checkpoint."""
        gallery, _names = self._targets()
        t0 = time.monotonic()
        with self._enroll_lock:
            from_version = self._gallery_version(gallery)
            emb, lab, val, size = build_fn()
            fault = (self._faults.on_cutover()
                     if self._faults is not None else None)
            if fault == "crash_before_record":
                raise InjectedCrashError("crash before cutover record: the "
                                         "stage is durable, the fleet stays "
                                         "on the old version")
            seq = self._wal_seq = self._wal_seq + 1
            self.wal.append_cutover(seq, from_version, int(to_version),
                                    rows=int(size), dim=int(emb.shape[1]))
            if fault == "crash_after_record":
                raise InjectedCrashError("crash after cutover record, "
                                         "before the in-memory swap: "
                                         "recovery must complete the "
                                         "cutover from the stage")
            gallery.load_snapshot(emb, lab, val, int(size),
                                  embedder_version=int(to_version))
        # Derived state rides the swap: retrain in the background
        # (single-flight); exact matching serves the interim. Outside the
        # enroll lock — the poke only flips quantizer staleness flags.
        poke = getattr(gallery, "_poke_quantizer", None)
        if poke is not None:
            poke()
        if self.registry is not None:
            # Keep the registry's embedder entry in step with the gallery
            # (the gallery stays that role's source of truth; the mirror
            # makes /registry and the checkpoint stamp coherent).
            self.registry.mirror_embedder(int(to_version))
        if self.metrics is not None:
            self.metrics.incr(mn.ROLLOUT_CUTOVERS)
            self.metrics.set_gauge(mn.ROLLOUT_EMBEDDER_VERSION,
                                   int(to_version))
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "cutover",
                             topic=LIFECYCLE_TOPIC, t0=t0,
                             dur=time.monotonic() - t0,
                             from_version=from_version,
                             to_version=int(to_version), rows=int(size),
                             seq=seq)
        return seq

    def adopt_wal_seq(self) -> int:
        """Seed the burned-sequence watermark from the existing WAL
        without running a full recovery (the offline ``--registry-swap``
        runbook path has no gallery to recover into): every record —
        aborts and corrupt-but-parseable ones included — advances the
        floor, exactly like recover()'s seeding, so a fence appended
        next never reuses a live or tombstoned seq."""
        _records, highest = self.wal.scan()
        with self._enroll_lock:
            self._wal_seq = max(self._wal_seq, int(highest))
            return self._wal_seq

    def perform_registry_cutover(self, role: str, to_version: int, *,
                                 config: Any = None,
                                 params_path: Optional[str] = None,
                                 params_sha256: Optional[str] = None,
                                 install_fn: Optional[Callable[[], None]]
                                 = None) -> int:
        """The atomic model-registry swap for a non-embedder role
        (``runtime.registry.RegistrySwapCoordinator`` drives this): under
        the enroll lock — so no enrollment can interleave between the
        fence and the swap, and no checkpoint can snapshot across it —

        1. the ``registry_cutover`` WAL fence record is appended (strict,
           fsynced) with the full post-swap registry stamp and the
           candidate params' sha256 — write-ahead: from this instant a
           crash recovers INTO the new version when the staged params
           verify, or cleanly abandons the swap when they don't (never a
           mix, never a guess);
        2. the manifest installs atomically (``ModelRegistry.install`` —
           tmp + rename + dirsync, monotonic per role);
        3. ``install_fn()`` publishes the new params in memory (model
           params are jit ARGUMENTS in the pipeline, so a
           same-architecture publish is one attribute store — keep it
           that cheap; it runs under the lock so every row appended
           after the fence was served by the new model).

        Returns the fence record's sequence. The caller MUST follow with
        a forced checkpoint — until it lands, read replicas park on the
        fence. Reuses the ``cutover`` fault boundary (crash_before_record
        / crash_after_record) so the chaos harness kills both windows."""
        if self.registry is None:
            raise RuntimeError("perform_registry_cutover needs an attached "
                               "ModelRegistry (attach_registry)")
        t0 = time.monotonic()
        with self._enroll_lock:
            from_version = self.registry.version(role)
            if int(to_version) <= from_version:
                raise ValueError(
                    f"registry versions are monotonic: {role} serves "
                    f"v{from_version}, refusing cutover to v{to_version}")
            stamp_after = self.registry.stamp()
            stamp_after[role] = int(to_version)
            if self._service is not None or self._gallery is not None:
                gallery, _names = self._targets()
                stamp_after["embedder"] = self._gallery_version(gallery)
            fault = (self._faults.on_cutover()
                     if self._faults is not None else None)
            if fault == "crash_before_record":
                raise InjectedCrashError(
                    "crash before registry_cutover record: the candidate "
                    "params are durable, the fleet stays on the old "
                    "version")
            seq = self._wal_seq = self._wal_seq + 1
            self.wal.append_registry_cutover(
                seq, role, from_version, int(to_version),
                registry=stamp_after, config=config,
                params_path=params_path, params_sha256=params_sha256)
            if fault == "crash_after_record":
                raise InjectedCrashError(
                    "crash after registry_cutover record, before the "
                    "manifest install: recovery must complete the swap "
                    "from the fence + staged params (or cleanly abandon)")
            self.registry.install(role, int(to_version), config=config,
                                  params_path=params_path,
                                  params_sha256=params_sha256)
            if install_fn is not None:
                install_fn()
        if self.metrics is not None:
            self.metrics.incr(mn.REGISTRY_SWAPS)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "registry_cutover",
                             topic=LIFECYCLE_TOPIC, t0=t0,
                             dur=time.monotonic() - t0, role=str(role),
                             from_version=from_version,
                             to_version=int(to_version), seq=seq)
        return seq

    # ---- checkpointing ----

    def checkpoint_due(self) -> bool:
        if time.monotonic() < self._ckpt_retry_at:
            return False  # failure backoff window (see checkpoint_now)
        if self._force_pending:
            return True
        if self._rows_since_ckpt >= self.checkpoint_wal_rows:
            return True
        return (self._rows_since_ckpt > 0
                and time.monotonic() - self._last_ckpt_t
                >= self.checkpoint_every_s)

    def tick(self) -> None:
        """Cheap per-loop-iteration threshold check (the serving loop
        calls this): a few comparisons in the common case."""
        if self.checkpoint_due():
            self.maybe_checkpoint()

    def maybe_checkpoint(self, force: bool = False) -> bool:
        """Spawn a background checkpoint when thresholds say so (or
        ``force``). Returns True when a worker was started. Single-flight:
        a THRESHOLD trigger overlapping an in-flight checkpoint is counted
        and dropped (the thresholds re-fire on their own); a FORCED one is
        latched instead — the in-flight checkpoint may predate the state
        change that forced this request (a reload swap), so the next tick
        must retry until a post-request snapshot lands."""
        if self._closed:
            return False
        if force:
            self._force_pending = True
        elif not self.checkpoint_due():
            return False
        if self._ckpt_lock.locked():
            if self.metrics is not None:
                self.metrics.incr(mn.CHECKPOINTS_SKIPPED_INFLIGHT)
            return False
        threading.Thread(target=self.checkpoint_now, daemon=True,
                         name="state-checkpoint").start()
        return True

    def checkpoint_now(self, wait: bool = False) -> bool:
        """Take one durable checkpoint synchronously: snapshot the gallery
        host mirrors (+ wal_seq, atomically vs. enrollments), serialize,
        install via the store, then compact the WAL below the captured
        sequence. Returns True on success; False when another checkpoint
        holds the single-flight guard (unless ``wait`` — the graceful-
        shutdown path must not skip its FINAL checkpoint just because a
        background one is mid-flight) or the save failed (counted
        ``checkpoint_failures`` — the previous checkpoint stays
        last-known-good). An ``InjectedCrashError`` propagates — it is a
        simulated kill, not a failure to handle."""
        if not self._ckpt_lock.acquire(blocking=wait):
            if self.metrics is not None:
                self.metrics.incr(mn.CHECKPOINTS_SKIPPED_INFLIGHT)
            return False
        # Claim any pending force request BEFORE snapshotting: this
        # attempt's snapshot postdates the request, so success satisfies
        # it; failure paths restore the latch so ticks keep retrying.
        claimed_force = self._force_pending
        self._force_pending = False
        span_t0 = time.monotonic()
        span = {"outcome": "crashed", "wal_seq": None, "rows": None}
        try:
            gallery, names = self._targets()
            # Bounded wait for async-grow staged rows: a snapshot taken
            # mid-grow would miss rows whose WAL records this checkpoint
            # claims to cover.
            wait_ready = getattr(gallery, "wait_ready", None)
            if wait_ready is not None:
                wait_ready(timeout=30.0)
            with self._enroll_lock:
                # Staged-rows guard, read under the enroll lock: staging
                # only happens inside append_enrollment (which holds this
                # lock), so pending can only DRAIN during this section —
                # pending == 0 here proves the snapshot below contains
                # every sequenced row. Nonzero (a grow still in flight,
                # wedged, or failed-and-awaiting-retry) means some records
                # <= wal_seq are NOT in the snapshot: writing a checkpoint
                # that claims them (or truncating their WAL records) would
                # lose acknowledged enrollments — DEFER instead; the
                # thresholds re-trigger, and until then the previous
                # checkpoint + full WAL stay consistent.
                if getattr(gallery, "pending_rows", 0):
                    if self.metrics is not None:
                        self.metrics.incr(mn.CHECKPOINTS_DEFERRED_PENDING)
                    logging.getLogger(__name__).warning(
                        "checkpoint deferred: %d staged rows not yet "
                        "landed", gallery.pending_rows)
                    self._force_pending = self._force_pending or claimed_force
                    # Short retry pause: each attempt already waited up to
                    # 30 s for the grow; don't spin a new worker per tick.
                    self._ckpt_retry_at = time.monotonic() + 5.0
                    span["outcome"] = "deferred"
                    return False
                wal_seq = self._wal_seq
                rows_at = self._rows_since_ckpt
                span.update(wal_seq=wal_seq, rows=rows_at)
                emb, lab, val, size = gallery.snapshot()
                # Embedder version captured in the SAME critical section
                # as the rows it stamps: a checkpoint header can never
                # claim one version over another version's snapshot. The
                # registry stamp rides the same section for the same
                # reason (a header straddling a registry swap must not
                # claim the new stamp over pre-swap rows).
                gver = self._gallery_version(gallery)
                reg_stamp = self._role_stamp()
                names_copy = [] if names is None else list(names)
                # IVF sidecar payload captured in the SAME critical
                # section: its assignments cover exactly the rows this
                # checkpoint covers, so keying it by this wal_seq is
                # sound (derived state; None when absent/not ready).
                snap_q = getattr(gallery, "snapshot_quantizer", None)
                qpayload = snap_q() if snap_q is not None else None
            from flax import serialization as flax_serialization

            payload = flax_serialization.msgpack_serialize(
                {"emb": emb, "lab": lab, "val": val})
            meta = {
                "kind": "gallery",
                "size": int(size),
                "capacity": int(emb.shape[0]),
                "dim": int(emb.shape[1]),
                "subject_names": names_copy,
                "wal_seq": wal_seq,
                "embedder_version": gver,
            }
            if reg_stamp is not None:
                meta["registry"] = {**reg_stamp, "embedder": gver}
            fault = (self._faults.on_checkpoint()
                     if self._faults is not None else None)
            try:
                self.store.save(payload, meta,
                                fault=fault if fault != "late" else None)
            except InjectedCrashError:
                raise
            except Exception:  # noqa: BLE001 — disk full, perms, ...
                logging.getLogger(__name__).exception("checkpoint save failed")
                if self.metrics is not None:
                    self.metrics.incr(mn.CHECKPOINT_FAILURES)
                # Exponential retry backoff: a persistently failing save
                # (full/unwritable dir) must not re-run a whole-gallery
                # snapshot + serialize on every serving-loop tick.
                self._force_pending = self._force_pending or claimed_force
                self._ckpt_retry_at = (time.monotonic()
                                       + self._ckpt_retry_backoff_s)
                self._ckpt_retry_backoff_s = min(
                    60.0, self._ckpt_retry_backoff_s * 2.0)
                span["outcome"] = "save_failed"
                return False
            if qpayload is not None:
                # Sidecar AFTER the checkpoint is durable (a crash in
                # between recovers checkpoint-without-sidecar -> retrain,
                # the safe direction); best-effort — derived state never
                # fails a checkpoint.
                from opencv_facerecognizer_tpu.parallel.quantizer import (
                    encode_sidecar,
                )

                try:
                    atomic_write_bytes(self.sidecar_path,
                                       encode_sidecar(qpayload, wal_seq))
                    if self.metrics is not None:
                        self.metrics.incr(mn.IVF_SIDECAR_WRITES)
                except OSError:
                    logging.getLogger(__name__).exception(
                        "quantizer sidecar write failed (checkpoint is "
                        "durable; recovery will retrain)")
                    if self.metrics is not None:
                        self.metrics.incr(mn.IVF_SIDECAR_ERRORS)
            if fault == "late":
                # The checkpoint landed; die before the WAL truncation —
                # the replay-dedup window the wal_seq header exists for.
                raise InjectedCrashError("crash after checkpoint, before "
                                         "WAL truncate")
            self.wal.truncate_below(wal_seq)
            with self._enroll_lock:
                self._rows_since_ckpt = max(0, self._rows_since_ckpt - rows_at)
            self._last_ckpt_t = time.monotonic()
            self._ckpt_retry_backoff_s = 1.0
            self._ckpt_retry_at = 0.0
            if self.metrics is not None:
                self.metrics.set_gauge(mn.WAL_ROWS, self._rows_since_ckpt)
            span["outcome"] = "ok"
            return True
        finally:
            self._ckpt_lock.release()
            if self.tracer is not None:
                # Emitted after the single-flight lock is released:
                # checkpoints are the slowest lifecycle machinery, and
                # their spans (outcome: ok/deferred/save_failed/crashed)
                # are what explains a recovery's starting point.
                self.tracer.emit(self.tracer.new_trace(), "checkpoint",
                                 topic=LIFECYCLE_TOPIC, t0=span_t0,
                                 dur=time.monotonic() - span_t0, **span)

    def close(self) -> None:
        self._closed = True
        self.wal.close()


def graceful_shutdown(service, state: Optional[StateLifecycle] = None,
                      supervisor=None, drain_timeout: float = 60.0) -> Dict[str, Any]:
    """The SIGTERM path (``ocvf-recognize`` wires this behind a signal
    handler): drain in-flight batches so accepted frames publish, stop the
    service (queued leftovers are journaled as ``closed`` drops — every
    admitted frame still lands in exactly one ledger bucket), take a final
    checkpoint, truncate the WAL, and report. The caller exits 0 when
    ``report["clean"]``."""
    drained = service.drain(timeout=drain_timeout)
    if supervisor is not None:
        supervisor.stop()
    else:
        service.stop()
    report: Dict[str, Any] = {"drained": drained}
    if state is not None:
        report["final_checkpoint"] = state.checkpoint_now(wait=True)
        state.close()
    ledger = service.ledger()
    report["ledger"] = ledger
    report["clean"] = bool(drained and abs(ledger["in_system"]) < 1e-6
                           and (state is None or report["final_checkpoint"]))
    # SIGTERM drain is a flight-recorder trigger: the final dump records
    # everything that was in flight through the shutdown (forced past the
    # rate limit — the LAST dump of a process must never be suppressed).
    tracer = getattr(service, "tracer", None)
    if tracer is not None:
        report["flight_dump"] = tracer.dump(
            "sigterm_drain", extra={"ledger": ledger,
                                    "drained": drained}, force=True)
    return report
