"""Prometheus text-format exposition of the shared ``Metrics`` surface
(served as ``GET /prom`` by ``runtime.expo``).

The JSON ``/metrics`` endpoint is for humans and tests; an external
orchestrator/scrape stack speaks the Prometheus text format
(``text/plain; version=0.0.4``).  ``render`` turns one atomic
``Metrics.export_state()`` snapshot into it:

- **counters** -> ``ocvf_<name>_total`` (TYPE counter);
- **gauges** -> ``ocvf_<name>`` (TYPE gauge);
- **histograms** (the rolling latency windows, merged over the full
  window) -> ``ocvf_<name>_seconds`` with cumulative ``_bucket{le=...}``
  series, ``_sum`` and ``_count`` — the boundaries are the shared
  ``utils.histogram.BUCKET_BOUNDS`` schema in seconds;
- **prefix families** are folded into labels: the registry's dynamic
  families (``frames_rejected_<reason>``, ``batcher_dropped_<reason>``,
  ``slo_burn_<objective>``, ``slo_events_<reason>``,
  ``track_flushes_<reason>``, ``transport_fault_<kind>``,
  ``router_rejected_<reason>``,
  ``stage_share_b<bucket>_<stage>``) become one metric each with a
  ``reason=`` / ``objective=`` / ``bucket=``+``stage=`` label instead of
  N single-sample families — the Prometheus-idiomatic shape, and the
  reason label values are escaped per the exposition rules (``\\\\``,
  ``\\"``, ``\\n``).

``lint_prometheus_text`` is a strict well-formedness check over the
rendered output — metric/label name grammar, one TYPE per family declared
before its samples, histogram bucket monotonicity, ``+Inf`` bucket ==
``_count``, float-parsable values — used by the exposition tests (and
usable against any exposition this process emits).  Rendering and linting
live in one module on purpose: the lint encodes the exact contract the
renderer claims.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

from opencv_facerecognizer_tpu.utils import metric_names as mn

#: every family name this module emits is prefixed with this namespace.
NAMESPACE = "ocvf"

#: prefix-family -> (metric name, label key(s)). ``stage_share_`` gets
#: special two-label parsing (``b<bucket>_<stage>``) below.
_LABEL_FAMILIES: Tuple[Tuple[str, str, str], ...] = (
    (mn.FRAMES_REJECTED_PREFIX, "frames_rejected", "reason"),
    (mn.BATCHER_DROPPED_PREFIX, "batcher_dropped", "reason"),
    (mn.SLO_EVENTS_PREFIX, "slo_events", "reason"),
    (mn.SLO_BURN_PREFIX, "slo_burn", "objective"),
    (mn.TRACK_FLUSHES_PREFIX, "track_flushes", "reason"),
    (mn.TRANSPORT_FAULTS_PREFIX, "transport_fault", "kind"),
    (mn.ROUTER_REJECTED_PREFIX, "router_rejected", "reason"),
)

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
_STAGE_SHARE_RE = re.compile(r"b(\d+)_([a-zA-Z0-9_]+)$")


def escape_label_value(value: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline (quotes are legal there)."""
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def _fmt(value: float) -> str:
    """Sample-value formatting: integers render bare (1 not 1.0), +Inf as
    ``+Inf``, NaN as ``NaN`` (both legal sample values in the format)."""
    f = float(value)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _sanitize(name: str) -> str:
    """Metric names on the shared surface are snake_case already; anything
    else (defensive) maps to underscores so the exposition never emits an
    ill-formed family name."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if not out or not _NAME_RE.match(out):
        out = "_" + out
    return out


class _Family:
    """One metric family being assembled: TYPE + HELP + sample lines."""

    def __init__(self, name: str, kind: str, help_text: str = ""):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.samples: List[str] = []

    def add(self, value: float, labels: Optional[Dict[str, str]] = None,
            suffix: str = "") -> None:
        label_s = ""
        if labels:
            inner = ",".join(
                f'{k}="{escape_label_value(v)}"'
                for k, v in sorted(labels.items()))
            label_s = "{" + inner + "}"
        self.samples.append(f"{self.name}{suffix}{label_s} {_fmt(value)}")

    def render(self) -> str:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {escape_help(self.help)}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        lines.extend(self.samples)
        return "\n".join(lines)


def _fold_family(name: str) -> Optional[Tuple[str, Dict[str, str]]]:
    """``(family metric name, labels)`` when ``name`` belongs to a
    registered dynamic prefix family; None for plain names."""
    if name.startswith(mn.STAGE_SHARE_PREFIX):
        m = _STAGE_SHARE_RE.match(name[len(mn.STAGE_SHARE_PREFIX):])
        if m:
            return "stage_share", {"bucket": m.group(1), "stage": m.group(2)}
        return None
    for prefix, family, label in _LABEL_FAMILIES:
        if name.startswith(prefix) and len(name) > len(prefix):
            return family, {label: name[len(prefix):]}
    return None


def render(metrics, namespace: str = NAMESPACE) -> str:
    """The full exposition for one ``Metrics`` object (module docstring).
    One atomic snapshot; deterministic ordering (sorted families) so
    scrapes diff cleanly."""
    counters, gauges, hists = metrics.export_state()
    families: Dict[str, _Family] = {}

    def family(raw: str, kind: str, labels=None, help_text: str = ""):
        folded = _fold_family(raw)
        if folded is not None:
            base, fold_labels = folded
            labels = {**(labels or {}), **fold_labels}
        else:
            base = _sanitize(raw)
        if kind == "counter":
            base += "_total"
        name = f"{namespace}_{base}"
        fam = families.get(name)
        if fam is None:
            fam = families[name] = _Family(name, kind, help_text)
        return fam, labels

    for raw, value in counters.items():
        fam, labels = family(raw, "counter")
        fam.add(value, labels)
    for raw, value in gauges.items():
        fam, labels = family(raw, "gauge")
        fam.add(value, labels)
    for raw, snap in hists.items():
        name = f"{namespace}_{_sanitize(raw)}_seconds"
        fam = families.setdefault(name, _Family(
            name, "histogram",
            "rolling log-bucket latency window (utils.histogram)"))
        cum = 0
        for bound, count in zip(snap["bounds"], snap["counts"]):
            cum += count
            fam.add(cum, {"le": _fmt(bound)}, suffix="_bucket")
        fam.add(snap["count"], {"le": "+Inf"}, suffix="_bucket")
        fam.add(snap["sum"], suffix="_sum")
        fam.add(snap["count"], suffix="_count")
    body = "\n".join(families[name].render() for name in sorted(families))
    return body + "\n" if body else ""


# ---- the format lint ----

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)(?: \d+)?$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"')


def _split_labels(blob: str) -> Optional[Dict[str, str]]:
    """Parse a label body strictly: comma-separated ``k="v"`` pairs with
    only legal escapes inside values. None on malformed input."""
    labels: Dict[str, str] = {}
    pos = 0
    while pos < len(blob):
        m = _LABEL_RE.match(blob, pos)
        if m is None:
            return None
        val = m.group("val")
        # Only \\, \", \n escapes are legal in label values — validated
        # PAIRWISE (a regex scan would misread the 'w' in '\\w' as an
        # escape: the first backslash already consumed the second).
        i = 0
        while i < len(val):
            if val[i] == "\\":
                if i + 1 >= len(val) or val[i + 1] not in '\\"n':
                    return None
                i += 2
            else:
                i += 1
        labels[m.group("key")] = val
        pos = m.end()
        if pos < len(blob):
            if blob[pos] != ",":
                return None
            pos += 1
    return labels


def _base_family(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def lint_prometheus_text(text: str) -> List[str]:
    """Well-formedness findings for one exposition body (empty list =
    clean): name/label grammar, exactly one TYPE per family and before
    its samples, histogram bucket monotonicity + ``+Inf`` == ``_count``,
    parsable sample values. This is the contract ``render`` claims; the
    exposition tests run it against the live ``/prom`` body."""
    findings: List[str] = []
    typed: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], str, int]] = []
    seen_sample_for: set = set()
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                findings.append(f"line {i}: malformed TYPE line: {line!r}")
                continue
            fam = parts[2]
            if not _NAME_RE.match(fam):
                findings.append(f"line {i}: bad family name {fam!r}")
            if fam in typed:
                findings.append(f"line {i}: duplicate TYPE for {fam}")
            if fam in seen_sample_for:
                findings.append(f"line {i}: TYPE for {fam} after its samples")
            typed[fam] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            findings.append(f"line {i}: unparseable sample: {line!r}")
            continue
        name = m.group("name")
        labels = _split_labels(m.group("labels") or "")
        if labels is None:
            findings.append(f"line {i}: malformed labels: {line!r}")
            continue
        for key in labels:
            if not _LABEL_NAME_RE.match(key):
                findings.append(f"line {i}: bad label name {key!r}")
        value = m.group("value")
        if value not in ("+Inf", "-Inf", "NaN"):
            try:
                float(value)
            except ValueError:
                findings.append(f"line {i}: unparseable value {value!r}")
        seen_sample_for.add(_base_family(name))
        samples.append((name, labels, value, i))
    # family/TYPE pairing: every sample's base family must be typed, and a
    # histogram family's samples must use the histogram suffixes.
    for name, labels, value, i in samples:
        base = _base_family(name)
        kind = typed.get(base) or typed.get(name)
        if kind is None:
            findings.append(f"line {i}: sample {name} has no TYPE")
            continue
        if kind == "histogram" and typed.get(name) is None:
            if not name.endswith(("_bucket", "_sum", "_count")):
                findings.append(
                    f"line {i}: histogram sample {name} lacks a "
                    f"_bucket/_sum/_count suffix")
            if name.endswith("_bucket") and "le" not in labels:
                findings.append(f"line {i}: _bucket sample without le label")
    # histogram coherence
    for fam, kind in typed.items():
        if kind != "histogram":
            continue
        buckets = [(lab.get("le"), val) for n, lab, val, _ in samples
                   if n == f"{fam}_bucket"]
        counts = [val for n, _, val, _ in samples if n == f"{fam}_count"]
        if not buckets:
            findings.append(f"histogram {fam} has no _bucket samples")
            continue
        if buckets[-1][0] != "+Inf":
            findings.append(f"histogram {fam} missing le=\"+Inf\" bucket")
        cum = [float(v) for _, v in buckets]
        if any(b > a for a, b in zip(cum[1:], cum)):
            findings.append(f"histogram {fam} bucket counts not cumulative")
        if counts and buckets[-1][0] == "+Inf" \
                and float(counts[0]) != cum[-1]:
            findings.append(
                f"histogram {fam} +Inf bucket {cum[-1]} != _count {counts[0]}")
        if not any(n == f"{fam}_sum" for n, _, _, _ in samples):
            findings.append(f"histogram {fam} missing _sum")
    return findings
