"""Live exposition surface: a read-only HTTP endpoint over the serving
runtime's observability state (observability layer, beside
``utils.tracing``).

Everything the runtime already knows about itself — ``Metrics.summary()``,
the admission ledger, the brownout level, the tracer's recent spans and
the derived stage-attribution gauges — was previously reachable only by
publishing a ``stats`` control command into the frame stream, which (a)
needs a connector client and (b) is unusable once the loop itself is the
thing being debugged. ``ExpoServer`` exposes the same state over plain
HTTP GET, served by its own threads so a wedged serving loop still
answers (the counters, ledger and spans are all lock-light reads):

======================  =====================================================
path                    payload
======================  =====================================================
``/``                   index: endpoints, brownout level, tracer stats
``/metrics``            ``Metrics.summary()`` (counters + gauges +
                        percentiles; empty windows report explicit nulls)
``/prom``               the same state in Prometheus text format
                        (``runtime.promtext.render``: counters/gauges/
                        rolling-histogram families, prefix families folded
                        into labels) — the scrape endpoint
``/health``             the SLO monitor's verdict (``runtime.slo``):
                        health state + per-objective short/long burn
                        rates + active watchdog events. HTTP 200 for
                        ok/warn, **503 for critical** (load balancers and
                        liveness probes key on the status alone); 200
                        with ``{"state": null}`` when no monitor is wired
``/ledger``             ``RecognizerService.ledger()`` — admitted /
                        completed / drops_by_reason / in_system
``/brownout``           ``{"level": n}``
``/spans``              recent spans: ``?topic=<ring>&limit=<max>``
                        (``n`` is an accepted alias; default: all topics
                        merged, newest 256; limit is bounds-checked —
                        non-integer or non-positive values answer 400,
                        values beyond ``SPAN_LIMIT_MAX`` are clamped)
``/attribution``        stage-attribution gauges, refreshed on read (see
                        ``fold_attribution``)
``/replicas``           the topic router's replica registry
                        (``runtime.replication.TopicRouter.registry``):
                        per-replica health, routed counts, observed topic
                        assignment; ``{"replicas": null}`` when no router
                        is wired
``/rollout``            the in-flight embedder rollout's status
                        (``runtime.rollout.RolloutCoordinator.status``):
                        phase, staged-re-embed watermark, dual-score
                        parity verdict; ``{"rollout": null}`` when none
``/tracks``             the temporal identity cache's track registry
                        (``runtime.tracker.IdentityTracker.registry``):
                        per-track stream/box/identity/confirmation state
                        plus hit-rate stats; ``{"tracks": null}`` when no
                        tracker is wired
======================  =====================================================

**Read-only contract**: every verb except GET is answered ``405 Method Not
Allowed`` — this surface can never mutate the service, by construction
(no handler writes anything). Requests/errors are counted on the shared
Metrics surface (``expo_requests`` / ``expo_errors``). The one nuance:
``/health`` reads the monitor's LAST verdict; the evaluation itself runs
on the serving loop's tick and (as a liveness backstop for wedged loops)
on this server's background refresh thread — never on a request thread.

**Stage attribution** (``fold_attribution``): two derived gauge families
registered in ``utils.metric_names``:

- ``device_busy_fraction`` — union of the tracer's recent ``ready_wait``
  batch-span intervals over a trailing window (the same interval-union
  technique ``scripts/trace_summary.py`` applies to offline device
  traces, fed from live spans — a periodic in-process probe instead of an
  xplane capture);
- ``stage_share_b<bucket>_<detect|crop|embed|match>`` — per-bucket stage
  shares of the fused device step. The stages run inside ONE jitted call
  at serving time (deliberately — the single-readback design), so live
  per-stage splits are unobservable; the shares come from the committed
  ablated-prefix measurements in ``BENCH_DETAIL.json``
  (``stage_attribution.per_batch``, measured by ``bench.py`` on this
  hardware) for exactly the buckets the dispatch spans show serving.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from opencv_facerecognizer_tpu.runtime.promtext import render as render_prom
from opencv_facerecognizer_tpu.runtime.slo import STATE_CRITICAL
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils import tracing

#: the fused step's in-device stages, in execution order (bench.py's
#: ablated-prefix stage table uses the same names).
DEVICE_STAGES = ("detect", "crop", "embed", "match")

#: hard cap on ``/spans`` ``limit=`` — a scrape cannot ask this surface
#: to serialize an unbounded span dump.
SPAN_LIMIT_MAX = 10000
SPAN_LIMIT_DEFAULT = 256


class _BadQuery(ValueError):
    """A malformed query parameter — mapped to HTTP 400 (the bounds-check
    contract: bad input is answered, never guessed at)."""

#: default bench artifact location: resolved relative to the REPO (two
#: levels above this module), not the process CWD — ``ocvf-recognize``
#: launched from any directory must still find the committed stage table.
DEFAULT_BENCH_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "BENCH_DETAIL.json")


def load_stage_quotes(bench_path: str = DEFAULT_BENCH_PATH
                      ) -> Dict[int, Dict[str, float]]:
    """Per-batch-size stage cost quotes (ms) from the committed bench
    artifact's ``stage_attribution.per_batch`` table; ``{}`` when the
    artifact (or the section) is absent — the gauges are then simply not
    set, never fabricated."""
    try:
        with open(bench_path) as fh:
            table = json.load(fh)["stage_attribution"]["per_batch"]
    except (OSError, KeyError, ValueError, TypeError):
        return {}
    out: Dict[int, Dict[str, float]] = {}
    for batch, stages in table.items():
        try:
            out[int(batch)] = {
                s: float(stages[s]["ms_per_batch"])
                for s in DEVICE_STAGES if s in stages
            }
        except (KeyError, TypeError, ValueError):
            continue
    return out


def fold_attribution(tracer, metrics, bench_path: str = DEFAULT_BENCH_PATH,
                     window_s: float = 30.0,
                     _quotes_cache: Dict[str, Any] = {}) -> Dict[str, float]:
    """Fold the tracer's recent batch spans into the derived
    stage-attribution gauges (module docstring); returns the values set.
    Cheap enough for a periodic background refresh: one ring snapshot +
    host arithmetic. A successfully loaded bench quote table is cached
    per path in the (deliberately shared) default-arg dict; a MISS is
    never cached — an artifact written after startup is picked up on the
    next refresh instead of being pinned absent for the process life."""
    out: Dict[str, float] = {}
    if tracer is None or metrics is None:
        return out
    spans = tracer.snapshot(topic=tracing.BATCH_TOPIC)
    busy = tracing.device_busy_fraction(spans, window_s=window_s)
    metrics.set_gauge(mn.DEVICE_BUSY_FRACTION, busy)
    out[mn.DEVICE_BUSY_FRACTION] = busy
    quotes = _quotes_cache.get(bench_path)
    if quotes is None:
        quotes = load_stage_quotes(bench_path)
        if quotes:
            _quotes_cache[bench_path] = quotes
    if not quotes:
        return out
    lo = time.monotonic() - window_s
    buckets = {s.get("bucket") for s in spans
               if s.get("stage") == "dispatch" and s["t0"] >= lo
               and s.get("bucket")}
    for bucket in buckets:
        # Nearest measured batch size stands in for unmeasured buckets
        # (the ladder defaults 8/32/128 match the bench sweep exactly).
        nearest = min(quotes, key=lambda b: abs(b - bucket))
        stage_ms = quotes[nearest]
        total = sum(stage_ms.values())
        if total <= 0:
            continue
        for stage, ms in stage_ms.items():
            share = ms / total
            metrics.set_gauge(mn.STAGE_SHARE_PREFIX + f"b{bucket}_{stage}",
                              share)
            out[mn.STAGE_SHARE_PREFIX + f"b{bucket}_{stage}"] = share
    return out


class ExpoServer:
    """Read-only HTTP exposition of the serving runtime's state (module
    docstring). ``port=0`` binds an ephemeral port (read ``.port`` after
    construction). ``start()`` spawns the HTTP threads plus a background
    gauge-refresh loop; ``stop()`` tears both down. Never wired into the
    serving hot path — a wedged loop still answers."""

    def __init__(self, service=None, tracer=None, metrics=None,
                 host: str = "127.0.0.1", port: int = 0,
                 refresh_s: float = 2.0,
                 bench_path: str = DEFAULT_BENCH_PATH,
                 slo=None, router=None, rollout=None, registry=None):
        self.service = service
        self.tracer = tracer if tracer is not None else getattr(
            service, "tracer", None)
        self.metrics = metrics if metrics is not None else getattr(
            service, "metrics", None)
        #: optional runtime.slo.SLOMonitor behind ``/health``; the refresh
        #: thread ticks it as a backstop so the verdict stays current even
        #: when the serving loop (its primary ticker) is wedged — which is
        #: exactly when an orchestrator polls /health hardest.
        self.slo = slo if slo is not None else getattr(service, "slo", None)
        #: optional runtime.replication.TopicRouter behind ``/replicas``:
        #: the replica registry (health, routed counts, observed topic
        #: assignment) as a read-only snapshot — what an orchestrator
        #: polls to see where failover moved the traffic.
        self.router = router
        #: optional runtime.rollout.RolloutCoordinator behind ``/rollout``:
        #: phase / staged watermark / parity-window verdict as a read-only
        #: snapshot (the ``rollout_*`` gauges carry the same numbers on
        #: /prom; this is the structured view an operator polls while
        #: deciding whether to cut over). Falls back to the service's
        #: attached coordinator so late attachment is visible.
        self.rollout = rollout
        #: optional runtime.registry.ModelRegistry behind ``/registry``:
        #: the served (role, version) manifest plus any in-flight swap
        #: coordinator's phase/parity — the structured view an operator
        #: polls during a detector/cascade swap (the ``model_version_*``
        #: and ``registry_*`` gauges carry the same numbers on /prom).
        #: Falls back to the service's attached registry, like rollout.
        self.registry = registry
        self.refresh_s = float(refresh_s)
        self.bench_path = bench_path
        self._started_t = time.monotonic()
        self._stop = threading.Event()
        self._refresh_thread: Optional[threading.Thread] = None
        self._thread: Optional[threading.Thread] = None
        expo = self

        class Handler(BaseHTTPRequestHandler):
            # Read-only contract: GET answers; every mutating verb is 405.
            def do_GET(self):  # noqa: N802 — http.server API
                expo._handle_get(self)

            def do_POST(self):  # noqa: N802
                expo._reject(self)

            do_PUT = do_DELETE = do_PATCH = do_POST  # noqa: N815

            def log_message(self, fmt, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]

    # ---- lifecycle ----

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="ocvf-expo")
        self._thread.start()
        self._refresh_thread = threading.Thread(target=self._refresh_loop,
                                                daemon=True,
                                                name="ocvf-expo-refresh")
        self._refresh_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._refresh_thread is not None:
            self._refresh_thread.join(timeout=2.0)
            self._refresh_thread = None

    def _refresh_loop(self) -> None:
        """Periodic fold of the derived gauges — off the hot path, so the
        exposition surface stays current even when nobody polls it (the
        gauges also land in the ``--metrics-jsonl`` stream)."""
        while not self._stop.wait(timeout=self.refresh_s):
            # The backstop tick runs FIRST and in its own try: a
            # persistently-failing attribution fold must not starve the
            # /health liveness backstop — that backstop exists for exactly
            # the moments when other parts of the system are misbehaving.
            if self.slo is not None:
                try:
                    # Backstop tick (interval-throttled inside the
                    # monitor): /health must reflect reality even when
                    # the serving loop stopped ticking.
                    self.slo.tick()
                except Exception:  # noqa: BLE001 — refresh must never die
                    logging.getLogger(__name__).exception(
                        "expo slo backstop tick failed")
                    if self.metrics is not None:
                        # slo_tick_errors, not expo_errors: the EVALUATION
                        # failed — same counter as the supervisor's
                        # backstop, so triage points at the monitor, not
                        # the HTTP surface.
                        self.metrics.incr(mn.SLO_TICK_ERRORS)
            try:
                fold_attribution(self.tracer, self.metrics,
                                 bench_path=self.bench_path)
            except Exception:  # noqa: BLE001 — refresh must never die
                logging.getLogger(__name__).exception(
                    "expo attribution refresh failed")
                if self.metrics is not None:
                    self.metrics.incr(mn.EXPO_ERRORS)

    # ---- request handling ----

    def payload(self, path: str, query: Dict[str, Any]) -> Dict[str, Any]:
        """The JSON body for one GET path; raises ``KeyError`` on unknown
        paths (mapped to 404). Pure reads — nothing here mutates the
        service (the read-only contract's enforcement by construction)."""
        service = self.service
        if path in ("/", "/index"):
            return {
                "endpoints": ["/", "/metrics", "/prom", "/health", "/ledger",
                              "/brownout", "/spans", "/attribution",
                              "/replicas", "/rollout", "/registry",
                              "/tracks"],
                "uptime_s": round(time.monotonic() - self._started_t, 1),
                "brownout_level": getattr(service, "brownout_level", None),
                "health": (self.slo.state if self.slo is not None else None),
                "tracer": (self.tracer.stats()
                           if self.tracer is not None else None),
            }
        if path == "/metrics":
            return dict(self.metrics.summary()) if self.metrics else {}
        if path == "/health":
            if self.slo is None:
                return {"state": None, "detail": "no SLO monitor wired"}
            return dict(self.slo.verdict())
        if path == "/ledger":
            return service.ledger() if service is not None else {}
        if path == "/brownout":
            return {"level": getattr(service, "brownout_level", None)}
        if path == "/spans":
            limit = self._span_limit(query)
            if self.tracer is None:
                return {"topics": [], "spans": []}
            topic = (query.get("topic") or [None])[0]
            return {"topics": self.tracer.topics(),
                    "spans": self.tracer.snapshot(topic=topic, limit=limit)}
        if path == "/attribution":
            return fold_attribution(self.tracer, self.metrics,
                                    bench_path=self.bench_path)
        if path == "/replicas":
            # Same unwired shape as /health: a null payload with a
            # pointer, never a 404 — the path is part of the contract.
            if self.router is None:
                return {"replicas": None, "detail": "no topic router wired"}
            return {"replicas": self.router.registry()}
        if path == "/rollout":
            coordinator = (self.rollout if self.rollout is not None
                           else getattr(service, "rollout", None))
            if coordinator is None:
                return {"rollout": None, "detail": "no rollout in flight"}
            return {"rollout": coordinator.status()}
        if path == "/registry":
            # Versioned model registry (ISSUE 18): the durable manifest's
            # served roles/versions plus any in-flight swap's phase and
            # detection-parity window. Same unwired shape as /rollout:
            # null payload with a pointer, never a 404.
            registry = (self.registry if self.registry is not None
                        else getattr(service, "registry", None))
            if registry is None:
                return {"registry": None, "detail": "no model registry wired"}
            swap = getattr(service, "registry_swap", None)
            return {"registry": registry.status(),
                    "swap": swap.status() if swap is not None else None}
        if path == "/tracks":
            # Temporal identity cache (ISSUE 17): the replica-local
            # track registry + hit-rate stats as a read-only snapshot —
            # what an operator polls to see WHO the cache thinks is in
            # each stream and how much device work it is absorbing.
            # Same unwired shape as /replicas: null payload, never 404.
            tracker = getattr(service, "tracker", None)
            if tracker is None:
                return {"tracks": None,
                        "detail": "no identity tracker wired"}
            return {"tracks": tracker.registry(),
                    "stats": tracker.stats()}
        raise KeyError(path)

    @staticmethod
    def _span_limit(query: Dict[str, Any]) -> int:
        """Bounds-checked ``limit=`` (alias ``n=``) for ``/spans``: a
        non-integer or non-positive value answers 400 (``_BadQuery``)
        instead of being silently defaulted; oversized asks clamp to
        ``SPAN_LIMIT_MAX``."""
        raw = (query.get("limit") or query.get("n") or [None])[0]
        if raw is None:
            return SPAN_LIMIT_DEFAULT
        try:
            limit = int(raw)
        except (TypeError, ValueError):
            raise _BadQuery(f"limit must be an integer, got {raw!r}")
        if limit <= 0:
            raise _BadQuery(f"limit must be positive, got {limit}")
        return min(limit, SPAN_LIMIT_MAX)

    def _handle_get(self, handler) -> None:
        if self.metrics is not None:
            self.metrics.incr(mn.EXPO_REQUESTS)
        parsed = urlparse(handler.path)
        content_type = "application/json"
        try:
            if parsed.path == "/prom":
                # Prometheus exposition is text, not JSON: rendered from
                # one atomic Metrics snapshot (runtime.promtext).
                text = render_prom(self.metrics) if self.metrics else ""
                self._respond(handler, 200, text.encode("utf-8"),
                              "text/plain; version=0.0.4; charset=utf-8")
                return
            body = self.payload(parsed.path, parse_qs(parsed.query))
            status = 200
            if (parsed.path == "/health"
                    and body.get("state_code") == STATE_CRITICAL):
                # Critical answers 503: a load balancer / liveness probe
                # reads the verdict from the status code alone.
                status = 503
        except _BadQuery as exc:
            body, status = {"error": str(exc)}, 400
        except KeyError:
            body, status = {"error": f"unknown path {parsed.path!r}"}, 404
        except Exception:  # noqa: BLE001 — a handler bug must answer 500
            logging.getLogger(__name__).exception("expo request failed")
            if self.metrics is not None:
                self.metrics.incr(mn.EXPO_ERRORS)
            body, status = {"error": "internal error"}, 500
        blob = json.dumps(body, default=repr).encode("utf-8")
        self._respond(handler, status, blob, content_type)

    @staticmethod
    def _respond(handler, status: int, blob: bytes,
                 content_type: str) -> None:
        try:
            handler.send_response(status)
            handler.send_header("Content-Type", content_type)
            handler.send_header("Content-Length", str(len(blob)))
            handler.end_headers()
            handler.wfile.write(blob)
        except OSError:
            pass  # client went away mid-response

    def _reject(self, handler) -> None:
        """Every non-GET verb: 405 — the read-only contract."""
        if self.metrics is not None:
            self.metrics.incr(mn.EXPO_REQUESTS)
        blob = b'{"error": "read-only endpoint: GET only"}'
        try:
            handler.send_response(405)
            handler.send_header("Allow", "GET")
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(blob)))
            handler.end_headers()
            handler.wfile.write(blob)
        except OSError:
            pass

