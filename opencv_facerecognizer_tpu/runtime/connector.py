"""Middleware connectors (SURVEY.md §1 L8, §5.8): the pluggable transport
boundary the reference put behind ``mwconnector/abstractconnector.py``.

Three transports ship:
- ``FakeConnector`` — in-process pub-sub; the test/bench transport (the
  SURVEY.md §4 prescription: the serving loop must be testable without ROS).
- ``JSONLConnector`` — newline-delimited JSON over arbitrary streams
  (stdin/stdout, files, sockets wrapped as files): the shippable default in
  an environment with no ROS/RSB. Frames travel as base64 raw bytes +
  shape/dtype.
- ``ROSConnector`` — the reference's primary transport (rosconnector.py
  equivalent): implemented against rospy/cv_bridge when present, raising a
  clear error here (no ROS in this image). Same interface, so swapping is a
  constructor change.

Messages are dicts; topics are strings. Handlers run on the connector's
dispatch thread — keep them cheap (the recognizer's handler just enqueues
into the FrameBatcher).
"""

from __future__ import annotations

import base64
import json
import threading
from typing import Any, Callable, Dict, IO, List, Optional

import numpy as np

Handler = Callable[[str, Dict[str, Any]], None]


def encode_frame(frame: np.ndarray) -> Dict[str, Any]:
    frame = np.ascontiguousarray(frame)
    return {
        "__frame__": base64.b64encode(frame.tobytes()).decode("ascii"),
        "shape": list(frame.shape),
        "dtype": str(frame.dtype),
    }


def decode_frame(obj: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(obj["__frame__"])
    return np.frombuffer(raw, dtype=np.dtype(obj["dtype"])).reshape(obj["shape"]).copy()


class MiddlewareConnector:
    """publish/subscribe over topics; start/stop lifecycle."""

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        raise NotImplementedError

    def subscribe(self, topic: str, handler: Handler) -> None:
        raise NotImplementedError

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class FakeConnector(MiddlewareConnector):
    """In-process pub-sub; synchronous dispatch on the publisher's thread.

    ``sent`` records every published message for assertions; ``inject`` is
    an alias of ``publish`` that reads better in tests.
    """

    def __init__(self):
        self._handlers: Dict[str, List[Handler]] = {}
        self._lock = threading.Lock()
        self.sent: List[tuple] = []

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        with self._lock:
            self.sent.append((topic, message))
            handlers = list(self._handlers.get(topic, ()))
        for handler in handlers:
            handler(topic, message)

    inject = publish

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def messages(self, topic: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [m for t, m in self.sent if t == topic]


class JSONLConnector(MiddlewareConnector):
    """One JSON object per line: {"topic": ..., "data": {...}}.

    A reader thread dispatches incoming lines to subscribed handlers;
    ``publish`` writes lines to the output stream. Malformed lines are
    counted and skipped, never fatal (SURVEY.md §5.3).
    """

    def __init__(self, in_stream: Optional[IO[str]] = None, out_stream: Optional[IO[str]] = None):
        self._in = in_stream
        self._out = out_stream
        self._handlers: Dict[str, List[Handler]] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.malformed_lines = 0

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        if self._out is None:
            return
        line = json.dumps({"topic": topic, "data": message})
        with self._lock:
            self._out.write(line + "\n")
            self._out.flush()

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def start(self) -> None:
        if self._in is None or self._thread is not None:
            return
        self._running = True
        self._thread = threading.Thread(target=self._read_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _read_loop(self) -> None:
        for line in self._in:
            if not self._running:
                break
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
                topic = obj["topic"]
                data = obj.get("data", {})
            except (json.JSONDecodeError, KeyError, TypeError):
                self.malformed_lines += 1
                continue
            with self._lock:
                handlers = list(self._handlers.get(topic, ()))
            for handler in handlers:
                handler(topic, data)


class ROSConnector(MiddlewareConnector):
    """The reference's ROS transport (SURVEY.md §2.1 "ROS recognizer node"):
    subscribe sensor_msgs/Image via cv_bridge, publish recognition results.
    Requires rospy; this environment ships without ROS, so construction
    fails with a pointer to the drop-in alternatives."""

    def __init__(self, image_topic: str = "/camera/image_raw",
                 result_topic: str = "/ocvfacerec/results"):
        try:
            import rospy  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "rospy is not installed in this environment; use JSONLConnector "
                "or FakeConnector, which implement the same MiddlewareConnector "
                "interface"
            ) from e
        self.image_topic = image_topic
        self.result_topic = result_topic
        # Full implementation intentionally deferred until a ROS environment
        # exists to run it against; the serving loop only depends on the
        # MiddlewareConnector interface.
