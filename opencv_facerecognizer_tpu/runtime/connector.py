"""Middleware connectors (SURVEY.md §1 L8, §5.8): the pluggable transport
boundary the reference put behind ``mwconnector/abstractconnector.py``.

Four transports ship:
- ``FakeConnector`` — in-process pub-sub; the test/bench transport (the
  SURVEY.md §4 prescription: the serving loop must be testable without ROS).
- ``JSONLConnector`` — newline-delimited JSON over arbitrary streams
  (stdin/stdout, files): the shippable default in an environment with no
  ROS/RSB. Frames travel as base64 raw bytes + shape/dtype. Signals EOF via
  the ``eof`` event so apps can shut down when the input stream ends.
- ``SocketConnector`` — the same JSONL framing over TCP: the second real
  remote transport (fills the slot the reference's RSB connector held,
  SURVEY.md §2.1 "RSB recognizer" — rsb itself is not installable here).
  Server mode accepts many clients and broadcasts published messages to all
  of them; client mode connects out.
- ``ROSConnector`` — the reference's primary transport (rosconnector.py
  equivalent): subscribes ``sensor_msgs/Image``, publishes results as JSON
  on a ``std_msgs/String`` topic. Import-guarded: constructing it without
  rospy raises with a pointer to the alternatives; the message-handling
  bodies are real and unit-tested against a mocked rospy.

Messages are dicts; topics are strings. Handlers run on the connector's
dispatch thread — keep them cheap (the recognizer's handler just enqueues
into the FrameBatcher).
"""

from __future__ import annotations

import base64
import io
import json
import os
import random
import select
import socket
import threading
import time
from typing import Any, Callable, Dict, IO, List, Optional

import numpy as np
from opencv_facerecognizer_tpu.utils import metric_names as mn

Handler = Callable[[str, Dict[str, Any]], None]

#: subscribe() under this topic receives EVERY message regardless of its
#: topic (the handler's first argument carries the real one). The
#: replication topic router forwards arbitrary camera topics wholesale —
#: without a wildcard it would have to know every topic up front.
WILDCARD_TOPIC = "*"


def encode_frame(frame: np.ndarray) -> Dict[str, Any]:
    frame = np.ascontiguousarray(frame)
    return {
        "__frame__": base64.b64encode(frame.tobytes()).decode("ascii"),
        "shape": list(frame.shape),
        "dtype": str(frame.dtype),
    }


def decode_frame(obj: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(obj["__frame__"])
    return np.frombuffer(raw, dtype=np.dtype(obj["dtype"])).reshape(obj["shape"]).copy()


class MiddlewareConnector:
    """publish/subscribe over topics; start/stop lifecycle."""

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        raise NotImplementedError

    def subscribe(self, topic: str, handler: Handler) -> None:
        raise NotImplementedError

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class FakeConnector(MiddlewareConnector):
    """In-process pub-sub; synchronous dispatch on the publisher's thread.

    ``sent`` records every published message for assertions; ``inject`` is
    an alias of ``publish`` that reads better in tests.
    """

    def __init__(self):
        self._handlers: Dict[str, List[Handler]] = {}
        self._lock = threading.Lock()
        self.sent: List[tuple] = []

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        with self._lock:
            self.sent.append((topic, message))
            handlers = list(self._handlers.get(topic, ()))
            if topic != WILDCARD_TOPIC:
                handlers += list(self._handlers.get(WILDCARD_TOPIC, ()))
        for handler in handlers:
            handler(topic, message)

    inject = publish

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def messages(self, topic: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [m for t, m in self.sent if t == topic]


def _parse_jsonl_line(line: str):
    """One JSONL wire line -> (topic, data) or None if malformed/empty."""
    line = line.strip()
    if not line:
        return None
    try:
        obj = json.loads(line)
        return obj["topic"], obj.get("data", {})
    except (json.JSONDecodeError, KeyError, TypeError):
        return "__malformed__", None


class _TopicDispatchConnector(MiddlewareConnector):
    """Shared handler registry + JSONL-line handling for the wire
    transports (JSONL/socket/ROS all dispatch the same way; one body).

    ``metrics`` (optional, a ``utils.metrics.Metrics``) mirrors the
    transport failure counters — ``connector_malformed_lines``,
    ``connector_peer_disconnects`` — onto the same surface the serving
    metrics live on, so failure-path tests (and a stats consumer) read one
    ledger instead of poking per-transport attributes."""

    def __init__(self, metrics=None):
        self._handlers: Dict[str, List[Handler]] = {}
        self._lock = threading.Lock()
        self.malformed_lines = 0
        self.metrics = metrics

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            # ocvf-lint: disable=metrics-registry -- thin None-guard shim; _count is itself in the rule's NAME_METHODS, so every caller's argument is validated against the registry at its own call site
            self.metrics.incr(name)

    def _dispatch(self, topic: str, data: Dict[str, Any]) -> None:
        with self._lock:
            handlers = list(self._handlers.get(topic, ()))
            if topic != WILDCARD_TOPIC:
                handlers += list(self._handlers.get(WILDCARD_TOPIC, ()))
        for handler in handlers:
            handler(topic, data)

    def _handle_line(self, line: str) -> None:
        parsed = _parse_jsonl_line(line)
        if parsed is None:
            return
        topic, data = parsed
        if data is None:
            self.malformed_lines += 1
            self._count(mn.CONNECTOR_MALFORMED_LINES)
            return
        self._dispatch(topic, data)


class JSONLConnector(_TopicDispatchConnector):
    """One JSON object per line: {"topic": ..., "data": {...}}.

    A reader thread dispatches incoming lines to subscribed handlers;
    ``publish`` writes lines to the output stream. Malformed lines are
    counted and skipped, never fatal (SURVEY.md §5.3).

    Lifecycle: ``eof`` is set when the reader finishes (input stream ended
    or ``stop()`` was called) — apps wait on it to shut down instead of
    spinning forever. For real-fd streams (stdin, pipes, socket files) the
    reader multiplexes the fd against a self-pipe with ``select``, so
    ``stop()`` genuinely unblocks a reader waiting for input. (Closing the
    stream from another thread — the obvious alternative — deadlocks on the
    buffered reader's internal lock in CPython.)
    """

    def __init__(
        self,
        in_stream: Optional[IO[str]] = None,
        out_stream: Optional[IO[str]] = None,
        metrics=None,
    ):
        super().__init__(metrics=metrics)
        self._in = in_stream
        self._out = out_stream
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._wake_r: Optional[int] = None
        self._wake_w: Optional[int] = None
        self.eof = threading.Event()

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        if self._out is None:
            return
        line = json.dumps({"topic": topic, "data": message})
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- this transport lock EXISTS to serialize whole lines onto the stream; no serving-path lock nests inside it
            try:
                self._out.write(line + "\n")
                self._out.flush()
            except (ValueError, OSError):
                # Stream closed during shutdown, or the consumer died
                # (BrokenPipeError) — either way publishing must never kill
                # the serving loop thread that called it.
                pass

    def start(self) -> None:
        if self._in is None or self._thread is not None:
            return
        self._running = True
        self._wake_r, self._wake_w = os.pipe()
        self._thread = threading.Thread(target=self._read_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._wake_w is not None:
            try:
                os.write(self._wake_w, b"x")  # wake a select()-blocked reader
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for fd in (self._wake_r, self._wake_w):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._wake_r = self._wake_w = None

    def _read_loop(self) -> None:
        stream = self._in
        try:
            fd = stream.fileno()
        except (OSError, AttributeError, ValueError, io.UnsupportedOperation):
            fd = None
        try:
            if fd is None:
                # In-memory stream (StringIO etc.): iteration never blocks.
                for line in stream:
                    if not self._running:
                        break
                    self._handle_line(line)
            else:
                self._read_loop_fd(fd)
        except ValueError:
            pass  # stream closed under us
        finally:
            self.eof.set()

    def _read_loop_fd(self, fd: int) -> None:
        """select() on the stream fd + the wake pipe; split lines manually
        (the raw fd bypasses the TextIO buffer, so all reads go through
        here — do not mix with stream.readline())."""
        buf = b""
        while self._running:
            ready, _, _ = select.select([fd, self._wake_r], [], [])
            if self._wake_r in ready:
                break  # stop() requested
            chunk = os.read(fd, 1 << 16)
            if not chunk:
                # True EOF: a final line without a trailing newline is
                # still a line (matches text-stream iteration semantics).
                if buf.strip():
                    self._handle_line(buf.decode("utf-8", errors="replace"))
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not self._running:
                    return
                self._handle_line(line.decode("utf-8", errors="replace"))


class SocketConnector(_TopicDispatchConnector):
    """JSONL framing over TCP — the second real remote transport.

    ``SocketConnector(port=N, listen=True)`` binds and accepts any number of
    clients; every ``publish`` is broadcast to all connected clients, every
    client line is dispatched to subscribed handlers. ``listen=False``
    connects out to ``(host, port)``. Either end speaks the exact
    JSONLConnector wire format, so a JSONL client can talk to a socket
    server through ``nc`` unchanged.

    Client mode survives server blips: a peer-initiated disconnect redials
    with bounded exponential backoff (``reconnect_attempts`` consecutive
    tries, ``reconnect_backoff_base_s`` doubling up to
    ``reconnect_backoff_max_s``; successes counted as
    ``connector_reconnects``). ``eof`` fires only once the budget is
    exhausted — not on the first blip, which previously killed the client
    connector permanently.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 listen: bool = False, metrics=None,
                 reconnect_attempts: int = 8,
                 reconnect_backoff_base_s: float = 0.05,
                 reconnect_backoff_max_s: float = 2.0,
                 reconnect_jitter: float = 0.5,
                 fault_injector=None, peer_name: Optional[str] = None):
        super().__init__(metrics=metrics)
        self.host = host
        self.port = port
        self.listen = listen
        # Transport fault boundary (ISSUE 16): when an injector is
        # installed, every published message crosses
        # ``on_transport(peer, "send", ...)`` before hitting the wire and
        # every received message crosses ``(peer, "recv", ...)`` before
        # dispatch — partition/half-open/slow/drop/duplicate/reorder all
        # land on the exact send/recv paths production traffic uses.
        # ``peer_name`` labels the remote end for per-peer injection;
        # defaults to "host:port".
        self._faults = fault_injector
        self._peer_name = peer_name
        # Reconnect backoff jitter: a deterministic exponential schedule
        # synchronizes a thundering herd (every peer of a restarted
        # replica redials on the same beat). Each delay is multiplied by
        # a uniform draw from [1 - jitter, 1 + jitter]; 0 restores the
        # deterministic schedule for tests that pin timing.
        self.reconnect_jitter = min(1.0, max(0.0, float(reconnect_jitter)))
        self._backoff_rng = random.Random()
        # Client-mode reconnect (bounded exponential backoff): a server
        # blip used to permanently kill the client connector — the read
        # loop ended, ``eof`` fired, and nothing ever dialed again. Now a
        # peer-initiated disconnect retries the connection up to
        # ``reconnect_attempts`` consecutive times (counted as
        # ``connector_reconnects`` on success), and ``eof`` fires only
        # once the budget is exhausted (or stop() is called). 0 disables.
        self.reconnect_attempts = max(0, int(reconnect_attempts))
        self.reconnect_backoff_base_s = float(reconnect_backoff_base_s)
        self.reconnect_backoff_max_s = float(reconnect_backoff_max_s)
        # Per-SOCKET send locks: interleaved partial writes from concurrent
        # publishes would splice two JSON lines into one corrupt frame, but
        # one stalled client (full TCP buffer) must not wedge publishes to
        # the healthy ones — so serialization is per socket, and each send
        # is deadline-bounded (see ``_send_deadline_s``); a client that
        # can't accept a payload in time is dropped like a dead one.
        self._send_locks: Dict[socket.socket, threading.Lock] = {}
        self._send_deadline_s = 2.0
        self._threads: List[threading.Thread] = []
        self._server_sock: Optional[socket.socket] = None
        self._client_socks: List[socket.socket] = []
        self._running = False
        self.eof = threading.Event()

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        if self.listen:
            self._server_sock = socket.create_server((self.host, self.port))
            self.port = self._server_sock.getsockname()[1]
            accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
            accept_thread.start()
            self._threads.append(accept_thread)
        else:
            # The FIRST connect stays synchronous (and raising): a server
            # that was never there is a configuration error the caller
            # should see immediately, unlike a mid-session blip.
            sock = socket.create_connection((self.host, self.port), timeout=10.0)
            sock.settimeout(None)
            self._register(sock)
            thread = threading.Thread(target=self._client_loop, args=(sock,),
                                      daemon=True)
            thread.start()
            self._threads.append(thread)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _addr = self._server_sock.accept()
            except OSError:
                break  # server socket closed by stop()
            self._attach(sock)
        self.eof.set()

    def _register(self, sock: socket.socket) -> bool:
        """Track a live socket for publish/teardown. Checked against
        ``_running`` UNDER the lock: stop() clears the registry under the
        same lock after flipping the flag, so a socket that registers here
        is guaranteed to be seen (and closed) by stop() — a reconnect
        completing concurrently with stop() must not leak a live
        connection past it. Returns False (socket closed) after stop."""
        with self._lock:
            if not self._running:
                try:
                    sock.close()
                except OSError:
                    pass
                return False
            self._client_socks.append(sock)
            self._send_locks[sock] = threading.Lock()
            return True

    def _attach(self, sock: socket.socket) -> None:
        if not self._register(sock):
            return
        thread = threading.Thread(target=self._read_loop, args=(sock,), daemon=True)
        thread.start()
        self._threads.append(thread)

    def _read_sock(self, sock: socket.socket) -> None:
        """Read one socket until it dies or stop(): dispatch lines, count
        a peer-initiated disconnect, and deregister the socket."""
        fh = sock.makefile("r", encoding="utf-8", errors="replace")
        try:
            # A peer that dies mid-message leaves a final line without a
            # newline; iteration still yields it, _handle_line counts it
            # malformed (truncated JSON never parses) — then the disconnect
            # itself is counted below. Two counters, two distinct faults.
            for line in fh:
                if not self._running:
                    break
                self._handle_line(line)
        except (OSError, ValueError):
            pass  # peer gone or socket closed during shutdown
        finally:
            if self._running:
                # Peer-initiated EOF/reset (our own stop() closes sockets
                # only after clearing _running): a flaky peer, counted.
                self._count(mn.CONNECTOR_PEER_DISCONNECTS)
            with self._lock:
                if sock in self._client_socks:
                    self._client_socks.remove(sock)
                self._send_locks.pop(sock, None)

    def _read_loop(self, sock: socket.socket) -> None:
        """Server-side per-client reader."""
        self._read_sock(sock)
        with self._lock:
            remaining = len(self._client_socks)
        if not self._running and remaining == 0:
            self.eof.set()

    def _client_loop(self, sock: socket.socket) -> None:
        """Client-side reader + reconnect supervisor: read until the
        connection dies, then redial with bounded exponential backoff.
        ``eof`` fires only when the reconnect budget is exhausted (the
        transport is genuinely gone) or stop() ends the session."""
        while True:
            self._read_sock(sock)
            if not self._running or self.reconnect_attempts <= 0:
                break
            sock = self._reconnect_with_backoff()
            if sock is None:
                break
        self.eof.set()

    def _reconnect_with_backoff(self) -> Optional[socket.socket]:
        """Up to ``reconnect_attempts`` redials, exponential backoff
        between them; sleeps in slices so stop() is honored promptly.
        Returns the registered socket, or None when the budget is spent."""
        for attempt in range(self.reconnect_attempts):
            delay = min(self.reconnect_backoff_max_s,
                        self.reconnect_backoff_base_s * 2 ** attempt)
            if self.reconnect_jitter > 0:
                delay *= self._backoff_rng.uniform(
                    1.0 - self.reconnect_jitter, 1.0 + self.reconnect_jitter)
            deadline = time.monotonic() + delay
            while self._running and time.monotonic() < deadline:
                time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
            if not self._running:
                return None
            try:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=10.0)
            except OSError:
                self._count(mn.CONNECTOR_RECONNECT_FAILURES)
                continue
            try:
                if sock.getsockname() == sock.getpeername():
                    # TCP self-connect (simultaneous open): dialing a dead
                    # EPHEMERAL port on loopback can land on the socket's
                    # own source port and "succeed" — a live connection to
                    # ourselves, not to a revived server. Treat as failure.
                    sock.close()
                    self._count(mn.CONNECTOR_RECONNECT_FAILURES)
                    continue
            except OSError:
                self._count(mn.CONNECTOR_RECONNECT_FAILURES)
                continue
            sock.settimeout(None)
            if not self._register(sock):
                return None  # stop() won the race; socket already closed
            self._count(mn.CONNECTOR_RECONNECTS)
            return sock
        return None

    def _send_bounded(self, sock: socket.socket, payload: bytes) -> bool:
        """Deadline-bounded send without touching the socket's blocking
        state (the read loop shares the socket): ``MSG_DONTWAIT`` makes each
        individual send non-blocking — a blocking-mode TCP ``send`` would
        otherwise park until the ENTIRE buffer is queued, which is exactly
        the wedge this guards against — and ``select`` bounds the wait for
        buffer space. Returns False when the deadline passes."""
        deadline = time.monotonic() + self._send_deadline_s
        view = memoryview(payload)
        while view:
            try:
                view = view[sock.send(view, socket.MSG_DONTWAIT):]
                continue
            except (BlockingIOError, InterruptedError):
                pass  # buffer full: wait (bounded) for space below
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            _, writable, _ = select.select((), (sock,), (), remaining)
            if not writable:
                return False
        return True

    def _transport_peer(self) -> str:
        return self._peer_name or f"{self.host}:{self.port}"

    def _transport_sink(self, kind: str) -> None:
        self._count(mn.TRANSPORT_FAULTS_PREFIX + kind)

    def _dispatch(self, topic: str, data: Dict[str, Any]) -> None:
        # Receive side of the transport fault boundary: a parsed wire
        # message crosses the injector before any handler sees it, so an
        # injected recv-drop/duplicate/reorder is indistinguishable from
        # the network doing it.
        if self._faults is None:
            super()._dispatch(topic, data)
            return
        for msg in self._faults.on_transport(self._transport_peer(), "recv",
                                             data, sink=self._transport_sink):
            super()._dispatch(topic, msg)

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        messages = [message]
        if self._faults is not None:
            # Send side of the transport boundary: a dropped/partitioned
            # message never reaches the wire; a duplicated one is framed
            # twice in the same payload (back-to-back lines, exactly what
            # a retransmit-happy link delivers).
            messages = self._faults.on_transport(
                self._transport_peer(), "send", message,
                sink=self._transport_sink)
            if not messages:
                return
        payload = "".join(
            json.dumps({"topic": topic, "data": m}) + "\n"
            for m in messages).encode()
        with self._lock:
            socks = [(s, self._send_locks[s]) for s in self._client_socks]
        dead = []
        for sock, lock in socks:
            with lock:
                try:
                    ok = self._send_bounded(sock, payload)
                except (OSError, ValueError):
                    # ValueError: select on a socket another thread closed
                    # mid-publish (fileno() == -1) — same as a dead client.
                    ok = False
                if not ok:
                    # Close while STILL holding the send lock: a concurrent
                    # publisher that already snapshotted this sock must get
                    # an immediate OSError, not append its line after our
                    # truncated one (spliced JSON frames on the wire).
                    # shutdown() first: close() alone does not interrupt a
                    # thread parked in recv() on Linux, so the read loop
                    # would stay blocked until the peer acts.
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        sock.close()
                    except OSError:
                        pass
            if not ok:
                dead.append(sock)
        if dead:
            with self._lock:
                for sock in dead:
                    if sock in self._client_socks:
                        self._client_socks.remove(sock)
                    self._send_locks.pop(sock, None)
            for _ in dead:
                self._count(mn.CONNECTOR_STALLED_CLIENTS_DROPPED)

    def stop(self) -> None:
        self._running = False
        if self._server_sock is not None:
            # shutdown() BEFORE close(): a thread blocked in accept()
            # holds a kernel reference to the listening socket, so a bare
            # close() leaves it listening — it would absorb one final
            # "ghost" connection (observed: a reconnecting client dials a
            # stopping server, connects, and parks forever on a socket
            # nobody will ever service). shutdown() wakes the accept with
            # an error and genuinely stops the listener.
            try:
                self._server_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server_sock.close()
            except OSError:
                pass
        with self._lock:
            socks = list(self._client_socks)
            self._client_socks.clear()
            self._send_locks.clear()
        for sock in socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        for thread in self._threads:
            thread.join(timeout=2.0)
        self._threads.clear()


def decode_ros_image(msg) -> np.ndarray:
    """sensor_msgs/Image -> float32 grayscale [H, W] without cv_bridge.

    Handles the encodings a camera driver actually emits: mono8/mono16
    directly, rgb8/bgr8/rgba8/bgra8 via the standard luma weights. Honors
    ``step`` (row stride) and ``is_bigendian`` for mono16.
    """
    h, w, step = int(msg.height), int(msg.width), int(msg.step)
    enc = str(msg.encoding).lower()
    raw = np.frombuffer(bytes(msg.data), dtype=np.uint8)
    channels = {"mono8": 1, "mono16": 2, "rgb8": 3, "bgr8": 3,
                "rgba8": 4, "bgra8": 4}
    if enc not in channels:
        raise ValueError(f"unsupported image encoding: {msg.encoding!r}")
    rows = raw.reshape(h, step)[:, : w * channels[enc]]
    if enc == "mono8":
        return rows.astype(np.float32)
    if enc == "mono16":
        dt = ">u2" if getattr(msg, "is_bigendian", 0) else "<u2"
        img16 = rows.reshape(h, w, 2).copy().view(dt)[..., 0]
        return (img16.astype(np.float32) / 257.0)  # 16-bit -> 0..255 scale
    c = channels[enc]
    rgb = rows.reshape(h, w, c)[..., :3].astype(np.float32)
    if enc.startswith("bgr"):
        rgb = rgb[..., ::-1]
    return rgb @ np.asarray([0.299, 0.587, 0.114], np.float32)


class ROSConnector(_TopicDispatchConnector):
    """The reference's ROS transport (SURVEY.md §2.1 "ROS recognizer node",
    BASELINE.json:5/:10 — the named target workload's transport).

    - ``sensor_msgs/Image`` on ``image_topic`` -> decoded grayscale frame
      dispatched to FRAME_TOPIC subscribers (same dict schema as the other
      connectors, so RecognizerService is transport-agnostic).
    - ``std_msgs/String`` JSON on ``control_topic`` -> control commands
      (enroll/stats — the reference's retrain/restart channel).
    - ``publish`` serializes result/status dicts as JSON into
      ``std_msgs/String`` on ``result_topic``/``status_topic`` (custom msg
      types would need a catkin build; String-JSON keeps the node drop-in).

    rospy is imported at construction and the node handles are injectable
    for tests (a mocked rospy module exercises the full body without ROS).
    """

    def __init__(
        self,
        image_topic: str = "/camera/image_raw",
        result_topic: str = "/ocvfacerec/results",
        control_topic: str = "/ocvfacerec/control",
        status_topic: str = "/ocvfacerec/status",
        node_name: str = "ocvf_recognizer",
        rospy_module=None,
    ):
        if rospy_module is None:
            try:
                import rospy as rospy_module  # type: ignore[no-redef]
            except ImportError as e:
                raise ImportError(
                    "rospy is not installed in this environment; use "
                    "JSONLConnector, SocketConnector, or FakeConnector, which "
                    "implement the same MiddlewareConnector interface"
                ) from e
        super().__init__()
        self._rospy = rospy_module
        self.image_topic = image_topic
        self.result_topic = result_topic
        self.control_topic = control_topic
        self.status_topic = status_topic
        self.node_name = node_name
        self._publishers: Dict[str, Any] = {}
        self._subscribers: List[Any] = []
        self._started = False
        self.frames_malformed = 0

    # Topic names on the app side (FRAME_TOPIC et al.) map onto the ROS
    # graph names given in the constructor.
    def _ros_topic_for(self, topic: str) -> str:
        from opencv_facerecognizer_tpu.runtime import recognizer as rec

        return {
            rec.RESULT_TOPIC: self.result_topic,
            rec.STATUS_TOPIC: self.status_topic,
        }.get(topic, topic)

    def start(self) -> None:
        if self._started:
            return
        rospy = self._rospy
        rospy.init_node(self.node_name, anonymous=True, disable_signals=True)
        self._string_cls = self._string_msg_cls()
        self._subscribers.append(
            rospy.Subscriber(self.image_topic, self._image_msg_cls(), self._on_image)
        )
        self._subscribers.append(
            rospy.Subscriber(self.control_topic, self._string_cls, self._on_control)
        )
        self._started = True

    @staticmethod
    def _string_msg_cls():
        try:
            from std_msgs.msg import String  # only exists beside rospy
        except ImportError:
            class String:  # stand-in with std_msgs/String's one field
                def __init__(self, data: str = ""):
                    self.data = data

        return String

    @staticmethod
    def _image_msg_cls():
        try:
            from sensor_msgs.msg import Image  # only exists beside rospy
        except ImportError:
            class Image:  # stand-in; only used as the Subscriber type arg
                pass

        return Image

    def _on_image(self, msg) -> None:
        from opencv_facerecognizer_tpu.runtime import recognizer as rec

        try:
            frame = decode_ros_image(msg)
        except Exception:  # noqa: BLE001 — malformed frame must not kill the node
            self.frames_malformed += 1
            # mirror onto the shared Metrics surface like the JSONL/socket
            # transports do, so one ledger covers every transport
            self._count(mn.CONNECTOR_MALFORMED_LINES)
            return
        stamp = getattr(getattr(msg, "header", None), "stamp", None)
        message = {**encode_frame(frame),
                   "meta": {"stamp": str(stamp) if stamp is not None else None}}
        self._dispatch(rec.FRAME_TOPIC, message)

    def _on_control(self, msg) -> None:
        from opencv_facerecognizer_tpu.runtime import recognizer as rec

        parsed = _parse_jsonl_line(getattr(msg, "data", ""))
        if parsed is None:
            return
        topic, data = parsed
        if data is None:
            # Accept bare command payloads too: {"cmd": "enroll", ...}
            try:
                data = json.loads(msg.data)
                topic = rec.CONTROL_TOPIC
            except (json.JSONDecodeError, TypeError):
                return
        self._dispatch(topic if topic != "__malformed__" else rec.CONTROL_TOPIC, data)

    def publish(self, topic: str, message: Dict[str, Any]) -> None:
        if not self._started:
            return
        ros_topic = self._ros_topic_for(topic)
        with self._lock:
            pub = self._publishers.get(ros_topic)
            if pub is None:
                pub = self._rospy.Publisher(ros_topic, self._string_cls, queue_size=16)
                self._publishers[ros_topic] = pub
        pub.publish(self._string_cls(data=json.dumps(message)))

    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def stop(self) -> None:
        for sub in self._subscribers:
            try:
                sub.unregister()
            except Exception:  # ocvf-lint: disable=swallowed-exception -- rospy teardown is best-effort by contract: a half-dead node handle raising here must not block shutdown, and there is nothing to recover
                pass
        self._subscribers.clear()
        self._started = False
