"""Durable dead-letter journal (overload layer §3).

Before this module, a dead-lettered or shed batch left behind exactly one
integer (a metrics counter) — a producer that wanted to retry the lost
frames had nothing to key on. ``DeadLetterJournal`` replaces that
count-only accounting with a bounded, rotating JSONL file: every
dead-lettered / shed / abandoned frame appends its metadata (the ``meta``
the producer sent, the enqueue timestamp, the priority when known) plus an
explicit reason, and ``replay`` walks the journal back so producers can
re-offer exactly what was lost.

Format — one JSON object per line::

    {"ts": <unix time>, "reason": "dead_letter", "frames":
     [{"meta": {...}, "enqueue_ts": <monotonic s|null>, "priority": <int|null>}]}

``enqueue_ts`` is ``time.monotonic()`` at batcher-put (the same stamp the
latency decomposition uses) — meaningful only relative to the writing
process; ``ts`` is wall-clock for cross-process correlation.

Rotation: when the active file exceeds ``max_bytes`` it is renamed to
``<path>.1`` (shifting older backups up, dropping the oldest beyond
``backups``) — the journal is a bounded flight recorder, not an archive.
Appends are serialized by a lock and flushed per record: a crash loses at
most the record being written.

A journal failure must never hurt serving — every write error is swallowed
after counting ``journal_errors`` on the (optional) metrics surface.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional


class DeadLetterJournal:
    def __init__(self, path: str, max_bytes: int = 4 << 20, backups: int = 2,
                 metrics=None):
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.backups = max(0, int(backups))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._fh = None
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)

    # ---- writing ----

    @staticmethod
    def frame_entry(meta: Any = None, enqueue_ts: Optional[float] = None,
                    priority: Optional[int] = None) -> Dict[str, Any]:
        return {"meta": meta, "enqueue_ts": enqueue_ts, "priority": priority}

    def append(self, reason: str, frames: List[Dict[str, Any]],
               **extra: Any) -> None:
        """Append one record for ``frames`` shed/dead-lettered for
        ``reason``. Never raises (see module docstring)."""
        record = {"ts": time.time(), "reason": str(reason),
                  "frames": list(frames)}
        if extra:
            record.update(extra)
        try:
            line = json.dumps(record, default=repr)
        except (TypeError, ValueError):
            line = json.dumps({"ts": record["ts"], "reason": record["reason"],
                               "frames": [], "encode_error": True})
        with self._lock:
            try:
                self._rotate_if_needed(len(line) + 1)
                if self._fh is None:
                    self._fh = open(self.path, "a", encoding="utf-8")
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError:
                if self.metrics is not None:
                    self.metrics.incr("journal_errors")
                return
        if self.metrics is not None:
            self.metrics.incr("journal_records")
            self.metrics.incr("journal_frames", len(record["frames"]))

    def _rotate_if_needed(self, incoming: int) -> None:
        """Caller holds the lock. Shift ``path -> path.1 -> path.2 ...``
        when the active file would exceed ``max_bytes``."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming <= self.max_bytes:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self.backups == 0:
            os.replace(self.path, self.path + ".old")
            os.remove(self.path + ".old")
            return
        oldest = f"{self.path}.{self.backups}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.backups - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # ---- reading / replay ----

    def _files_oldest_first(self) -> List[str]:
        files = [f"{self.path}.{i}" for i in range(self.backups, 0, -1)]
        files.append(self.path)
        return [f for f in files if os.path.exists(f)]

    def records(self) -> Iterator[Dict[str, Any]]:
        """Every journal record, oldest first (rotated files included).
        Malformed lines (a crash mid-write) are skipped, not fatal."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            files = self._files_oldest_first()
        for path in files:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            yield json.loads(line)
                        except json.JSONDecodeError:
                            continue
            except OSError:
                continue

    def replay(self, handler: Callable[[Dict[str, Any]], None],
               reasons: Optional[tuple] = None) -> int:
        """Call ``handler(frame_entry)`` for every journaled frame (each
        entry augmented with its record's ``reason`` and ``ts``), oldest
        first; returns the number of frames replayed. The producer-side
        retry hook: a handler typically re-offers each frame's ``meta`` to
        its source. A raising handler stops the replay (the caller decides
        whether a partial retry is acceptable)."""
        n = 0
        for record in self.records():
            if reasons is not None and record.get("reason") not in reasons:
                continue
            for entry in record.get("frames", ()):
                handler({**entry, "reason": record.get("reason"),
                         "ts": record.get("ts")})
                n += 1
        return n


def main(argv=None) -> int:
    """Tiny ops helper: print a journal's records (oldest first)."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        description="dump a dead-letter journal as JSON lines")
    parser.add_argument("path")
    parser.add_argument("--reason", help="only records with this reason")
    args = parser.parse_args(argv)
    journal = DeadLetterJournal(args.path)
    for record in journal.records():
        if args.reason and record.get("reason") != args.reason:
            continue
        sys.stdout.write(json.dumps(record) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
