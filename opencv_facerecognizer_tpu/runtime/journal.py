"""Durable journals: a bounded rotating JSONL base + the dead-letter
journal built on it (overload layer §3; the enrollment WAL in
``runtime.state_store`` reuses the same machinery).

Before this module, a dead-lettered or shed batch left behind exactly one
integer (a metrics counter) — a producer that wanted to retry the lost
frames had nothing to key on. ``DeadLetterJournal`` replaces that
count-only accounting with a bounded, rotating JSONL file: every
dead-lettered / shed / abandoned frame appends its metadata (the ``meta``
the producer sent, the enqueue timestamp, the priority when known) plus an
explicit reason, and ``replay`` walks the journal back so producers can
re-offer exactly what was lost.

Format — one JSON object per line::

    {"ts": <unix time>, "reason": "dead_letter", "frames":
     [{"meta": {...}, "enqueue_ts": <monotonic s|null>, "priority": <int|null>}]}

``enqueue_ts`` is ``time.monotonic()`` at batcher-put (the same stamp the
latency decomposition uses) — meaningful only relative to the writing
process; ``ts`` is wall-clock for cross-process correlation.

Rotation: when the active file exceeds ``max_bytes`` it is renamed to
``<path>.1`` (shifting older backups up, dropping the oldest beyond
``backups``) — the journal is a bounded flight recorder, not an archive.
Appends are serialized by a lock and flushed per record: a crash loses at
most the record being written.

**Fsync policy** (``fsync=``, shared with the enrollment WAL and exposed
as ``ocvf-recognize --journal-fsync``):

- ``"never"`` (default — the original behavior): flush to the kernel per
  record, never ``fsync``; a process crash loses at most the torn tail
  record, a POWER cut can lose everything the kernel hadn't written back.
- ``"interval"``: additionally ``fsync`` at most once per
  ``fsync_interval_s`` — bounds the power-cut window to that interval
  while appends continue (the sync rides the next append); after a burst
  STOPS, the un-synced tail persists at ``close()``/``sync()`` or the
  next append, whichever comes first — an idle open journal's last
  sub-interval of records is the residual exposure.
- ``"always"``: ``fsync`` after every append — an append that returned is
  durable (what the enrollment WAL runs with: its acknowledgments promise
  crash-survival).

A DEAD-LETTER journal failure must never hurt serving — every write error
is swallowed after counting ``journal_errors`` on the (optional) metrics
surface. The WAL subclass uses ``strict=True`` appends instead: a failed
write there must abort the enrollment acknowledgment, not vanish.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional
from opencv_facerecognizer_tpu.utils import metric_names as mn

#: accepted fsync policies, in increasing durability order.
FSYNC_POLICIES = ("never", "interval", "always")


class RotatingJournal:
    """Append-only JSONL file with bounded rotation and an fsync policy —
    the shared machinery under ``DeadLetterJournal`` and the enrollment
    WAL (``state_store.EnrollmentWAL``). Subclasses own record semantics;
    this class owns the file: locking, rotation, flush/fsync, and the
    oldest-first reader that skips torn lines."""

    def __init__(self, path: str, max_bytes: int = 4 << 20, backups: int = 2,
                 metrics=None, fsync: str = "never",
                 fsync_interval_s: float = 1.0, fault_injector=None,
                 error_counter: str = mn.JOURNAL_ERRORS,
                 shed_counter: str = mn.JOURNAL_SHED):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in {FSYNC_POLICIES}")
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.backups = max(0, int(backups))
        self.metrics = metrics
        self.fsync = fsync
        self.fsync_interval_s = float(fsync_interval_s)
        #: chaos hook (runtime.faults.FaultInjector): the ``storage``
        #: boundary fires inside ``_append_locked``, before the real
        #: write, so an injected ENOSPC/EIO lands on the exact OSError
        #: path a full/broken disk produces. None in production.
        self._faults = fault_injector
        #: per-sink accounting names (ISSUE 15): the dead-letter journal
        #: and the span-JSONL sink share this class but must not share a
        #: counter — triage has to tell which sink is failing/shedding.
        #: Both are registry constants chosen at construction.
        self.error_counter = str(error_counter)
        self.shed_counter = str(shed_counter)
        #: degraded-durability shed hook: when set and truthy, NON-STRICT
        #: appends are dropped before touching the disk (counted on
        #: ``shed_counter``) — a dying disk's remaining bytes belong to
        #: the WAL, not the flight recorders. Strict appends (the WAL
        #: itself) never consult it.
        self.shed_fn = None
        self._last_fsync_t = 0.0
        self._lock = threading.Lock()
        self._fh = None
        # Set when an append failed partway (ENOSPC can land a partial
        # line before raising): the next append must first terminate the
        # torn bytes with a newline, or a SUCCESSFUL, fsynced,
        # acknowledged record would glue onto them and become one
        # unparseable line — silent loss of acked data on replay.
        self._needs_seal = False
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)

    # ---- writing ----

    def append_line(self, line: str, strict: bool = False) -> bool:
        """Append one pre-encoded JSON line (rotating first if needed),
        flushed and fsynced per policy. Returns True on success; an OSError
        is counted (``journal_errors``) and either swallowed (default —
        the dead-letter posture: a journal failure must never hurt
        serving) or re-raised (``strict`` — the WAL posture: a failed
        append must fail the acknowledgment that depends on it)."""
        if not strict and self.shed_fn is not None and self.shed_fn():
            # Degraded-durability shed (non-strict sinks only): no disk
            # touched, exact per-sink accounting instead of one swallowed
            # OSError per attempt against a disk already known broken.
            if self.metrics is not None:
                self.metrics.incr(self.shed_counter)  # ocvf-lint: disable=metrics-registry -- constructor-bound per-sink constant (JOURNAL_SHED / TRACE_SPANS_SHED), both registered
            return False
        with self._lock:
            try:
                self._append_locked(line)
            except OSError:
                self._needs_seal = True  # partial bytes may have landed
                if self.metrics is not None:
                    self.metrics.incr(self.error_counter)  # ocvf-lint: disable=metrics-registry -- constructor-bound per-sink constant (JOURNAL_ERRORS / TRACE_SPAN_ERRORS), both registered
                if strict:
                    raise
                return False
        return True

    def _append_locked(self, line: str, newline: bool = True) -> None:
        """Caller holds the lock. Raw write + flush + policy fsync. A
        pending seal (previous failed append) is prepended as a newline in
        the SAME write, so the torn bytes end up an isolated unparseable
        line instead of a prefix of this record."""
        if self._faults is not None:
            # Chaos storage boundary: fired BEFORE any byte so an injected
            # ENOSPC/EIO takes the exact path a real full disk does (the
            # caller's OSError handling + seal bookkeeping); slow_fsync
            # stalls here, where a real slow device would.
            self._faults.on_storage("journal_append")
        self._rotate_if_needed(len(line) + 2)
        if self._fh is None:
            # First open of a PRE-EXISTING file: a previous process's
            # ENOSPC/crash may have left a partial final line with no
            # newline — detect it now and latch the seal, so the remnant
            # is terminated in the same write as this record's prefix
            # ("sealed at next open") instead of becoming its prefix.
            self._latch_torn_tail_locked()
            self._fh = open(self.path, "a", encoding="utf-8")
        prefix = "\n" if self._needs_seal else ""
        self._fh.write(prefix + line + ("\n" if newline else ""))
        self._needs_seal = False  # the write (incl. the seal) landed
        self._fh.flush()
        self._fsync_locked()

    def _latch_torn_tail_locked(self) -> None:
        """Caller holds the lock, the write handle is not open yet. If the
        file's last byte is not a newline (an ENOSPC/crash-torn append
        from a previous process), set ``_needs_seal`` and count
        ``journal_torn_tails`` — the torn remnant stays one isolated
        unparseable line (skipped by ``records``; never replayed, never
        double-counted) instead of gluing onto the next record."""
        if self._needs_seal:
            return  # an in-process failed append already latched it
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                torn = fh.read(1) != b"\n"
        except OSError:
            return  # no file yet (fresh journal): nothing to seal
        if torn:
            self._needs_seal = True
            if self.metrics is not None:
                self.metrics.incr(mn.JOURNAL_TORN_TAILS)

    def _fsync_locked(self) -> None:
        if self.fsync == "never" or self._fh is None:
            return
        now = time.monotonic()
        if (self.fsync == "interval"
                and now - self._last_fsync_t < self.fsync_interval_s):
            return
        os.fsync(self._fh.fileno())
        self._last_fsync_t = now

    def sync(self) -> None:
        """Force an fsync of the active file regardless of policy (the
        graceful-shutdown path wants durability NOW)."""
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- fsync-before-return IS this method's contract; the journal lock only serializes journal writers, never a serving-path lock
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._last_fsync_t = time.monotonic()
                except OSError:
                    if self.metrics is not None:
                        self.metrics.incr(self.error_counter)  # ocvf-lint: disable=metrics-registry -- constructor-bound per-sink constant, registered

    def _rotate_if_needed(self, incoming: int) -> None:
        """Caller holds the lock. Shift ``path -> path.1 -> path.2 ...``
        when the active file would exceed ``max_bytes``."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming <= self.max_bytes:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self.backups == 0:
            os.replace(self.path, self.path + ".old")
            os.remove(self.path + ".old")
            return
        oldest = f"{self.path}.{self.backups}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.backups - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")

    def close(self) -> None:
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- shutdown path: the final fsync must complete before the handle is torn down, and nothing else runs at close
            if self._fh is not None:
                try:
                    if self.fsync != "never":
                        # "interval" only fsyncs on SUBSEQUENT appends: the
                        # tail of a burst would otherwise never be synced
                        # once traffic stops — close is the last chance to
                        # honor the bounded-window promise.
                        self._fh.flush()
                        os.fsync(self._fh.fileno())
                except OSError:
                    pass
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # ---- reading ----

    def _files_oldest_first(self) -> List[str]:
        files = [f"{self.path}.{i}" for i in range(self.backups, 0, -1)]
        files.append(self.path)
        return [f for f in files if os.path.exists(f)]

    def records(self) -> Iterator[Dict[str, Any]]:
        """Every journal record, oldest first (rotated files included).
        Malformed lines are skipped, not fatal — corruption-total: invalid
        UTF-8 bytes (``errors="replace"``), unparseable JSON, and lines
        that parse to a non-object (``null``, a bare number) all read as
        damage to skip, never an exception out of a recovery/replay loop."""
        with self._lock:  # ocvf-lint: boundary-block=blocking-under-lock -- one flush so replay sees buffered tail rows; bounded, and replay is an offline/recovery path
            if self._fh is not None:
                self._fh.flush()
            files = self._files_oldest_first()
        for path in files:
            try:
                with open(path, "r", encoding="utf-8",
                          errors="replace") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            record = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if isinstance(record, dict):
                            yield record
            except OSError:
                continue


class DeadLetterJournal(RotatingJournal):
    """Bounded rotating journal of dead-lettered / shed / abandoned frames
    (module docstring). Non-strict by design: a journal failure is counted
    and swallowed — serving never dies to its flight recorder."""

    # ---- writing ----

    @staticmethod
    def frame_entry(meta: Any = None, enqueue_ts: Optional[float] = None,
                    priority: Optional[int] = None,
                    trace_id: Optional[int] = None,
                    stage: Optional[str] = None) -> Dict[str, Any]:
        """One journaled frame: producer ``meta``, batcher enqueue stamp,
        priority class — plus the frame's ``trace_id`` and the lifecycle
        ``stage`` it died at (e.g. ``batcher.stale``,
        ``readback.dead_letter``), so ``replay`` can reconstruct exactly
        where each dropped frame's lifecycle ended and correlate it with a
        flight-recorder dump's spans."""
        return {"meta": meta, "enqueue_ts": enqueue_ts,
                "priority": priority, "trace_id": trace_id, "stage": stage}

    def append(self, reason: str, frames: List[Dict[str, Any]],
               **extra: Any) -> None:
        """Append one record for ``frames`` shed/dead-lettered for
        ``reason``. Never raises (see module docstring)."""
        record = {"ts": time.time(), "reason": str(reason),
                  "frames": list(frames)}
        if extra:
            record.update(extra)
        try:
            line = json.dumps(record, default=repr)
        except (TypeError, ValueError):
            line = json.dumps({"ts": record["ts"], "reason": record["reason"],
                               "frames": [], "encode_error": True})
        if not self.append_line(line, strict=False):
            return
        if self.metrics is not None:
            self.metrics.incr(mn.JOURNAL_RECORDS)
            self.metrics.incr(mn.JOURNAL_FRAMES, len(record["frames"]))

    # ---- replay ----

    def replay(self, handler: Callable[[Dict[str, Any]], None],
               reasons: Optional[tuple] = None) -> int:
        """Call ``handler(frame_entry)`` for every journaled frame (each
        entry augmented with its record's ``reason`` and ``ts``), oldest
        first; returns the number of frames replayed. The producer-side
        retry hook: a handler typically re-offers each frame's ``meta`` to
        its source. A raising handler stops the replay (the caller decides
        whether a partial retry is acceptable)."""
        n = 0
        for record in self.records():
            if reasons is not None and record.get("reason") not in reasons:
                continue
            for entry in record.get("frames", ()):
                handler({**entry, "reason": record.get("reason"),
                         "ts": record.get("ts")})
                n += 1
        return n


def main(argv=None) -> int:
    """Tiny ops helper: print a journal's records (oldest first). Each
    frame entry carries its ``trace_id`` and death ``stage`` (plus the
    record-level ``dump`` path when a flight-recorder dump accompanied a
    dead-letter), so ``--trace`` answers "where did frame X die" and
    ``--stage`` answers "what died at stage Y" (exact match, e.g.
    ``batcher.stale`` / ``readback.dead_letter`` — the same stage strings
    the settle spans carry, so journal rows and flight dumps correlate).
    Filters compose (AND)."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        description="dump a dead-letter journal as JSON lines")
    parser.add_argument("path")
    parser.add_argument("--reason", help="only records with this reason")
    parser.add_argument("--trace", type=int, default=None,
                        help="only records holding a frame with this "
                             "trace id (prints where that frame died)")
    parser.add_argument("--stage", default=None,
                        help="only records holding a frame that died at "
                             "this lifecycle stage (exact match, e.g. "
                             "batcher.stale, readback.dead_letter, "
                             "dispatch.brownout_trim)")
    args = parser.parse_args(argv)
    journal = DeadLetterJournal(args.path)
    for record in journal.records():
        if args.reason and record.get("reason") != args.reason:
            continue
        if args.trace is not None and not any(
                f.get("trace_id") == args.trace
                for f in record.get("frames", ())):
            continue
        if args.stage is not None and not any(
                f.get("stage") == args.stage
                for f in record.get("frames", ())):
            continue
        sys.stdout.write(json.dumps(record) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
