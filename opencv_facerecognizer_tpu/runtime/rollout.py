"""Zero-downtime embedder rollout: version-fenced state, crash-safe
staged re-embed, dual-score parity gating, atomic fleet cutover.

Enrollment before this module was append-only against ONE frozen
embedder: every gallery row, WAL record and checkpoint implicitly lived
in that model's embedding space. A production fleet retrains (the
multibatch metric-learning recipe in ``runtime.trainer`` produces the
fine-tuned model), and rolling the new embedder out live has exactly one
hard invariant: **no published score is ever computed against a gallery
mixing embedder versions** — a query embedded by model A compared to
rows embedded by model B is silent identity corruption, worse than
downtime. This module makes the version an explicit, fenced, durable
property of the state machinery PR 4/6/10 built:

- **Version fencing** — ``ShardedGallery.embedder_version`` names the
  one space every row in a served shard set lives in. ``StateLifecycle``
  stamps it into checkpoint headers and every WAL enrollment row, and
  fails an enrollment closed (``EmbedderVersionMismatchError``, inside
  the enroll lock, before any sequence is burned) when the embedding's
  version disagrees with the serving gallery's. Replay, read replicas
  and the offline verifier all refuse to apply a row across the fence.
- **Crash-safe background re-embed** (``ReEmbedStage``) — accumulated
  enrollments are re-embedded off the hot thread into a staged shard
  set: an append-only, fsync-always progress journal of fixed chunks
  (``rollout/stage-v<N>.jsonl``), each crc-checked, with a torn tail
  sealed at open exactly like the WAL. A kill at ANY point resumes from
  the last durable watermark — re-embedding is deterministic over the
  append-only source rows, so a re-staged chunk is bit-identical and
  half-migrated rows are never served (the live gallery is untouched
  until cutover).
- **Dual-score parity window** (``DualScoreParity``) — before cutover is
  allowed, old and new embedder score side-by-side on live traffic
  (face crops sampled off the publish path, scored on the rollout
  thread): top-1 identity agreement over a sliding window must clear a
  gated threshold with a minimum sample count. Exported as ``rollout_*``
  gauges on the shared Metrics surface (hence ``/prom``), with
  ``runtime.slo.rollout_parity_objective`` feeding /health.
- **Atomic cutover** (``RolloutCoordinator.cutover`` ->
  ``StateLifecycle.perform_cutover``) — under the enroll lock: the
  final enrollment delta is staged durably, a ``cutover`` WAL fence
  record lands (strict fsync, write-ahead), then the gallery installs
  the new-space arrays + version in ONE epoch-fenced publish
  (in-flight batches keep the arrays they captured; the IVF quantizer
  invalidates and retrains in the background — PR 6's derived-state
  lifecycle rides the swap). A forced checkpoint follows; until it
  lands, recovery COMPLETES the cutover from the durable stage. Read
  replicas see the fence in the WAL tail, stop applying, and re-anchor
  on the new-version checkpoint through the PR-10 resync path — the
  ``TopicRouter`` cordons each replica through its re-anchor so its
  topics drain to peers and fleet-wide completed-frames never hits
  zero. **Rollback is the same mechanism pointed at the prior space**:
  a new rollout whose ``reembed_fn`` maps rows back (``rollback()``).

Crash matrix (what ``scripts/chaos_soak.py --scenario rollout``
asserts): kill mid-re-embed -> resume from the watermark, old version
serves untouched; kill after the fence record, before the swap or its
checkpoint -> recovery installs the staged set at the new version,
zero acked loss; kill a reader mid-re-anchor -> its replacement resyncs
onto the new checkpoint; and in every interleaving, each published
result's ``embedder_version`` stamp moves old -> new exactly once per
replica, never mixed.
"""

from __future__ import annotations

import base64
import binascii
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
from opencv_facerecognizer_tpu.runtime.state_store import (
    EmbedderVersionMismatchError,
    StateLifecycle,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC

__all__ = [
    "DualScoreParity",
    "EmbedderVersionMismatchError",
    "ReEmbedStage",
    "RolloutCoordinator",
    "RolloutGateError",
    "RolloutStateError",
    "load_stage",
    "stage_path",
]

logger = logging.getLogger(__name__)

#: state-dir subdirectory holding staged re-embed progress journals.
ROLLOUT_DIR = "rollout"

#: phase gauge codes (``rollout_phase`` on /prom).
PHASE_CODES = {"idle": 0, "staging": 1, "parity": 2, "ready": 3,
               "cutover": 4, "done": 5}


class RolloutStateError(RuntimeError):
    """Durable rollout state (the staged shard set) is missing or damaged
    where correctness requires it — e.g. recovery found a fsynced cutover
    fence but the stage file no longer covers the promised rows. Fails
    CLOSED: serving a mixed- or partially-migrated gallery is the one
    outcome this subsystem exists to prevent."""


class RolloutGateError(RuntimeError):
    """Cutover refused: the staged re-embed is not caught up or the
    dual-score parity window has not cleared its gate. ``force=True``
    overrides (the operator's explicit judgment call)."""


def stage_path(state_dir: str, to_version: int) -> str:
    return os.path.join(str(state_dir), ROLLOUT_DIR,
                        f"stage-v{int(to_version)}.jsonl")


def _l2norm(rows: np.ndarray) -> np.ndarray:
    rows = np.asarray(rows, np.float32)
    return rows / np.maximum(np.linalg.norm(rows, axis=-1, keepdims=True),
                             1e-12)


def _decode_stage_chunk(record: Dict[str, Any]
                        ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
    """Validate + decode one parsed stage chunk -> (start, emb, labels),
    or None when the record fails its crc/shape checks (a torn-then-
    sealed remnant, or media damage — the caller decides whether a gap
    is fatal)."""
    try:
        raw = base64.b64decode(record["emb"], validate=True)
        if (binascii.crc32(raw) & 0xFFFFFFFF) != record["crc32"]:
            return None
        n, dim = int(record["n"]), int(record["dim"])
        emb = np.frombuffer(raw, np.float32)
        if emb.size != n * dim:
            return None
        labels = np.asarray(record["labels"], np.int32)
        if labels.shape[0] != n:
            return None
        return int(record["start"]), emb.reshape(n, dim), labels
    except (KeyError, TypeError, ValueError, binascii.Error):
        return None


def _read_stage_file(path: str) -> Tuple[Optional[Dict[str, Any]],
                                         Dict[int, Tuple[np.ndarray,
                                                         np.ndarray]], int]:
    """Parse one stage journal -> (begin record or None, {start: (emb,
    labels)} with later duplicates winning, torn/invalid line count).
    Pure read — shared by the owning ``ReEmbedStage`` (resume) and the
    recovery-side ``load_stage`` (which must never write)."""
    begin: Optional[Dict[str, Any]] = None
    chunks: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    bad = 0
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            lines = fh.read().split("\n")
    except OSError:
        return None, {}, 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
            if not isinstance(record, dict):
                raise ValueError("not an object")
        except (json.JSONDecodeError, ValueError):
            bad += 1
            continue
        kind = record.get("kind")
        if kind == "stage_begin" and begin is None:
            begin = record
        elif kind == "stage":
            decoded = _decode_stage_chunk(record)
            if decoded is None:
                bad += 1
                continue
            start, emb, labels = decoded
            chunks[start] = (emb, labels)
    return begin, chunks, bad


def _coverage(chunks: Dict[int, Tuple[np.ndarray, np.ndarray]]) -> int:
    """Contiguous watermark: the largest W with rows [0, W) fully staged.
    Chunks may overlap after a crash-resume (the re-staged chunk is
    bit-identical — re-embedding is deterministic over append-only
    source rows), so walk starts in order and extend greedily."""
    watermark = 0
    for start in sorted(chunks):
        n = chunks[start][0].shape[0]
        if start <= watermark < start + n or start == watermark:
            watermark = max(watermark, start + n)
        elif start > watermark:
            break  # gap: nothing past it is contiguous
    return watermark


def load_stage(state_dir: str, to_version: int,
               expect_rows: Optional[int] = None,
               expect_dim: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Recovery-side loader: the staged shard set as ``(embeddings
    [rows, dim], labels [rows])`` — strictly read-only (the recovering
    process may be completing another process's cutover). Raises
    ``RolloutStateError`` when the file is absent, mis-headed, or does
    not contiguously cover ``expect_rows`` — the fence record promised
    those rows were durable, so anything less is media damage and the
    caller must fail closed, never serve a partial migration."""
    path = stage_path(state_dir, to_version)
    begin, chunks, _bad = _read_stage_file(path)
    if begin is None:
        raise RolloutStateError(
            f"stage file {path} is missing or headerless, but a durable "
            f"cutover record references it — cannot complete the cutover "
            f"(restore the rollout/ directory or roll back)")
    if int(begin.get("to_version", -1)) != int(to_version) or (
            expect_dim is not None
            and int(begin.get("dim", -1)) != int(expect_dim)):
        raise RolloutStateError(
            f"stage file {path} header disagrees with the cutover record "
            f"(header: {begin}, wanted to_version={to_version} "
            f"dim={expect_dim})")
    watermark = _coverage(chunks)
    rows = int(expect_rows) if expect_rows is not None else watermark
    if watermark < rows:
        raise RolloutStateError(
            f"stage file {path} covers only {watermark} contiguous rows "
            f"of the {rows} the cutover record promised — damaged stage; "
            f"refusing a partial migration")
    dim = int(begin["dim"])
    emb = np.zeros((rows, dim), np.float32)
    labels = np.zeros((rows,), np.int32)
    for start in sorted(chunks):
        c_emb, c_lab = chunks[start]
        if start >= rows:
            continue
        end = min(rows, start + c_emb.shape[0])
        emb[start:end] = c_emb[:end - start]
        labels[start:end] = c_lab[:end - start]
    return emb, labels


class ReEmbedStage:
    """Crash-safe staged re-embed progress for one target version
    (module docstring). Append-only JSONL, fsync on every chunk: the
    watermark visible after ANY kill is exactly the set of chunks whose
    append returned. Single-writer by contract — the rollout thread (or
    the cutover's locked finalize) owns it."""

    def __init__(self, state_dir: str, to_version: int, dim: int,
                 from_version: int = 1, metrics=None, fault_injector=None):
        self.state_dir = str(state_dir)
        self.to_version = int(to_version)
        self.from_version = int(from_version)
        self.dim = int(dim)
        self.metrics = metrics
        self._faults = fault_injector
        self.path = stage_path(state_dir, to_version)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._chunks: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self.watermark = 0
        self.resumed = False
        self._load_or_begin()

    # ---- durable file plumbing ----

    def _append_line(self, text: str, newline: bool = True) -> None:
        if self._faults is not None:
            # Storage boundary (disk stays broken — distinct from the
            # ``stage`` kill-point faults): an injected ENOSPC/EIO raises
            # out of stage_chunk before the watermark advances, exactly
            # like a real full disk; the rollout loop's existing
            # stage-error handling owns it.
            self._faults.on_storage("stage_append")
        with open(self.path, "a", encoding="utf-8") as fh:  # ocvf-lint: disable=non-atomic-write -- append-only progress journal (the WAL discipline): records are immutable once fsynced, torn tails are sealed at open and skipped by the crc'd reader; atomic-rewrite would destroy the resumability this file exists for
            fh.write(text + ("\n" if newline else ""))
            fh.flush()
            os.fsync(fh.fileno())

    def _seal_torn_tail(self) -> None:
        try:
            if not os.path.getsize(self.path):
                return
            with open(self.path, "rb+") as fh:
                fh.seek(-1, os.SEEK_END)
                if fh.read(1) != b"\n":
                    fh.write(b"\n")
                    fh.flush()
                    os.fsync(fh.fileno())
        except OSError:
            if self.metrics is not None:
                self.metrics.incr(mn.ROLLOUT_STAGE_ERRORS)

    def _load_or_begin(self) -> None:
        if os.path.exists(self.path):
            self._seal_torn_tail()
            begin, chunks, _bad = _read_stage_file(self.path)
            if (begin is not None
                    and int(begin.get("to_version", -1)) == self.to_version
                    and int(begin.get("dim", -1)) == self.dim):
                self._chunks = chunks
                self.watermark = _coverage(chunks)
                self.resumed = bool(chunks)
                if self.resumed and self.metrics is not None:
                    self.metrics.incr(mn.ROLLOUT_STAGE_RESUMES)
                if self.resumed:
                    logger.info(
                        "rollout stage v%d resumed at watermark %d "
                        "(%s)", self.to_version, self.watermark, self.path)
                return
            # Config drift (different target dim/version reusing the
            # file name): the old progress is unusable — start clean.
            logger.warning("rollout stage %s header mismatch; restaging "
                           "from zero", self.path)
            try:
                os.remove(self.path)
            except OSError:
                pass
        self._append_line(json.dumps({
            "kind": "stage_begin", "to_version": self.to_version,
            "from_version": self.from_version, "dim": self.dim,
            "ts": time.time()}))

    # ---- staging ----

    def stage_chunk(self, start: int, emb: np.ndarray,
                    labels: np.ndarray) -> None:
        """Durably append one contiguous chunk of re-embedded rows
        (raises on write failure or injected kill — the watermark only
        advances once the fsync returned)."""
        emb = np.ascontiguousarray(np.asarray(emb, np.float32))
        labels = np.asarray(labels, np.int32)
        if emb.ndim != 2 or emb.shape[1] != self.dim \
                or emb.shape[0] != labels.shape[0]:
            raise ValueError(f"stage chunk shape mismatch: emb {emb.shape} "
                             f"labels {labels.shape} dim {self.dim}")
        raw = emb.tobytes()
        line = json.dumps({
            "kind": "stage", "start": int(start), "n": int(emb.shape[0]),
            "dim": self.dim, "labels": [int(v) for v in labels],
            "emb": base64.b64encode(raw).decode("ascii"),
            "crc32": binascii.crc32(raw) & 0xFFFFFFFF, "ts": time.time(),
        })
        fault = self._faults.on_stage() if self._faults is not None else None
        if fault == "crash":
            raise InjectedCrashError("crash before stage chunk append")
        if fault == "torn":
            self._append_line(line[:max(1, len(line) // 2)], newline=False)
            raise InjectedCrashError("torn stage chunk append")
        self._append_line(line)
        self._chunks[int(start)] = (emb, labels)
        self.watermark = _coverage(self._chunks)
        if self.metrics is not None:
            self.metrics.incr(mn.ROLLOUT_STAGE_CHUNKS)
            self.metrics.set_gauge(mn.ROLLOUT_STAGED_ROWS, self.watermark)

    def arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """The staged set up to the watermark as (emb, labels)."""
        emb = np.zeros((self.watermark, self.dim), np.float32)
        labels = np.zeros((self.watermark,), np.int32)
        for start in sorted(self._chunks):
            c_emb, c_lab = self._chunks[start]
            if start >= self.watermark:
                continue
            end = min(self.watermark, start + c_emb.shape[0])
            emb[start:end] = c_emb[:end - start]
            labels[start:end] = c_lab[:end - start]
        return emb, labels

    def discard(self) -> None:
        """Delete the progress journal — ONLY after the post-cutover
        checkpoint landed (until then, recovery needs this file to
        complete a fenced-but-uncheckpointed cutover)."""
        try:
            os.remove(self.path)
        except OSError:
            pass


class DualScoreParity:
    """Old-vs-new embedder agreement over a sliding window of live
    queries (module docstring). Pure host math on the galleries' f32
    truth — it runs on the rollout thread, never the hot path."""

    def __init__(self, old_embed_fn: Callable[[np.ndarray], np.ndarray],
                 new_embed_fn: Callable[[np.ndarray], np.ndarray],
                 threshold: float = 0.98, min_samples: int = 32,
                 window: int = 512, metrics=None):
        self.old_embed_fn = old_embed_fn
        self.new_embed_fn = new_embed_fn
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.metrics = metrics
        self._agreements: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()

    @staticmethod
    def _top1(queries: np.ndarray, rows: np.ndarray,
              labels: np.ndarray) -> np.ndarray:
        """Top-1 gallery LABEL per query (lowest-index tie-break, like
        the serving kernels); -1 when the gallery side is empty."""
        if rows.shape[0] == 0 or queries.shape[0] == 0:
            return np.full((queries.shape[0],), -1, np.int64)
        sims = queries @ rows.T
        return labels[np.argmax(sims, axis=1)]

    def score(self, crops: np.ndarray, old_rows: np.ndarray,
              old_labels: np.ndarray, new_rows: np.ndarray,
              new_labels: np.ndarray) -> int:
        """Score one batch of query crops through BOTH embedders against
        their respective galleries; returns samples recorded."""
        crops = np.asarray(crops, np.float32)
        if crops.ndim == 2:
            crops = crops[None]
        old_q = _l2norm(np.asarray(self.old_embed_fn(crops), np.float32))
        new_q = _l2norm(np.asarray(self.new_embed_fn(crops), np.float32))
        old_top = self._top1(old_q, old_rows, old_labels)
        new_top = self._top1(new_q, new_rows, new_labels)
        with self._lock:
            for a, b in zip(old_top, new_top):
                self._agreements.append(1.0 if (a == b and a >= 0) else 0.0)
            samples = len(self._agreements)
            agreement = (sum(self._agreements) / samples) if samples else 0.0
        if self.metrics is not None:
            self.metrics.set_gauge(mn.ROLLOUT_PARITY_SAMPLES, samples)
            self.metrics.set_gauge(mn.ROLLOUT_PARITY_AGREEMENT,
                                   round(agreement, 4))
        return int(old_top.shape[0])

    @property
    def samples(self) -> int:
        with self._lock:
            return len(self._agreements)

    @property
    def agreement(self) -> float:
        with self._lock:
            if not self._agreements:
                return 0.0
            return sum(self._agreements) / len(self._agreements)

    @property
    def disagreement(self) -> float:
        """1 - agreement once the window has data; 0.0 below the sample
        floor (no data is not a breach — the SLO gauge contract)."""
        with self._lock:
            n = len(self._agreements)
            if n < self.min_samples:
                return 0.0
            return 1.0 - sum(self._agreements) / n

    def ok(self) -> bool:
        with self._lock:
            n = len(self._agreements)
            return (n >= self.min_samples
                    and sum(self._agreements) / n >= self.threshold)


class RolloutCoordinator:
    """Drives one embedder rollout end to end (module docstring):
    background staged re-embed with durable resume, the dual-score
    parity window over live traffic, and the gated atomic cutover.

    ``reembed_fn(rows) -> rows'`` maps the OLD gallery's (normalized,
    host-truth) rows into the new embedder's space — in production the
    fine-tuned model re-extracting from the enrollment source store, in
    the chaos harness a fixed linear map. It must be deterministic over
    its input: a crash-resumed chunk re-stages from the same source rows
    and must reproduce the same bytes. ``old_embed_fn``/``new_embed_fn``
    embed live QUERY crops for the parity window (both optional — without
    them the parity gate never opens and cutover needs ``force=True``).
    """

    def __init__(self, state: StateLifecycle, gallery,
                 reembed_fn: Callable[[np.ndarray], np.ndarray],
                 to_version: int, *,
                 old_embed_fn: Optional[Callable] = None,
                 new_embed_fn: Optional[Callable] = None,
                 parity_threshold: float = 0.98,
                 parity_min_samples: int = 32,
                 parity_window: int = 512,
                 chunk_rows: int = 256,
                 live_sample_interval_s: float = 0.05,
                 face_size: Optional[Tuple[int, int]] = None,
                 metrics=None, tracer=None, fault_injector=None):
        self.state = state
        self.gallery = gallery
        self.reembed_fn = reembed_fn
        self.to_version = int(to_version)
        self.from_version = int(getattr(gallery, "embedder_version", 1))
        if self.to_version <= self.from_version:
            raise ValueError(
                f"to_version {to_version} must exceed the serving version "
                f"{self.from_version} (versions are monotonic; a rollback "
                f"is a NEW version whose space equals the prior one)")
        self.chunk_rows = max(1, int(chunk_rows))
        self.metrics = metrics
        self.tracer = tracer
        self.face_size = face_size
        # Kept verbatim so rollback() can clone the FULL configuration
        # (the parity deque only remembers its maxlen indirectly).
        self._parity_window = int(parity_window)
        self._fault_injector = fault_injector
        self.stage = ReEmbedStage(state.state_dir, self.to_version,
                                  dim=int(gallery.dim),
                                  from_version=self.from_version,
                                  metrics=metrics,
                                  fault_injector=fault_injector)
        self.parity = (DualScoreParity(old_embed_fn, new_embed_fn,
                                       threshold=parity_threshold,
                                       min_samples=parity_min_samples,
                                       window=parity_window, metrics=metrics)
                       if old_embed_fn is not None
                       and new_embed_fn is not None else None)
        self._phase = "idle"
        self._live_q: deque = deque(maxlen=64)
        self._live_lock = threading.Lock()
        self._live_interval_s = float(live_sample_interval_s)
        self._last_live_t = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # reembed_fn comes in two shapes: ``fn(rows)`` (a space-to-space
        # map — the chaos harness's linear transform) and
        # ``fn(rows, start)`` (a source-store re-extract that needs the
        # row indices — ``TheTrainer.make_reembed_fn``). Sniffed once.
        try:
            import inspect

            self._reembed_wants_start = len(
                inspect.signature(reembed_fn).parameters) >= 2
        except (TypeError, ValueError):
            self._reembed_wants_start = False
        self._set_phase("idle")

    def _reembed(self, rows: np.ndarray, start: int) -> np.ndarray:
        if self._reembed_wants_start:
            return self.reembed_fn(rows, start)
        return self.reembed_fn(rows)

    # ---- phase bookkeeping ----

    def _set_phase(self, phase: str) -> None:
        self._phase = phase
        if self.metrics is not None:
            self.metrics.set_gauge(mn.ROLLOUT_PHASE, PHASE_CODES[phase])
            self.metrics.set_gauge(mn.ROLLOUT_TOTAL_ROWS,
                                   int(self.gallery.size))
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "rollout_phase",
                             topic=LIFECYCLE_TOPIC, phase=phase,
                             to_version=self.to_version,
                             staged=self.stage.watermark,
                             total=int(self.gallery.size))

    @property
    def phase(self) -> str:
        return self._phase

    @property
    def caught_up(self) -> bool:
        return self.stage.watermark >= int(self.gallery.size)

    # ---- staged re-embed ----

    def run_stage_step(self) -> bool:
        """Stage one chunk of not-yet-re-embedded rows; returns True when
        a chunk was staged (False = caught up). Reads the gallery's host
        truth via ``snapshot()`` — source rows are append-only, so a
        chunk staged from one snapshot stays valid forever."""
        emb, lab, _val, size = self.gallery.snapshot()
        start = self.stage.watermark
        if start >= size:
            return False
        if self._phase in ("idle", "done"):
            self._set_phase("staging")
        end = min(size, start + self.chunk_rows)
        new_rows = _l2norm(self._reembed(emb[start:end], start))
        if new_rows.shape != (end - start, self.stage.dim):
            raise RolloutStateError(
                f"reembed_fn returned {new_rows.shape}, expected "
                f"{(end - start, self.stage.dim)}")
        self.stage.stage_chunk(start, new_rows, lab[start:end])
        return True

    def run_stage(self, max_chunks: Optional[int] = None) -> int:
        """Stage until caught up (or ``max_chunks``); returns chunks
        staged. The synchronous form — chaos kills land mid-loop."""
        staged = 0
        while (max_chunks is None or staged < max_chunks):
            if not self.run_stage_step():
                break
            staged += 1
        if self.caught_up and self._phase in ("idle", "staging"):
            self._set_phase("parity" if self.parity is not None else "ready")
        return staged

    # ---- the rollout thread ----

    def start(self) -> None:
        """Run staging + parity scoring on a background daemon thread —
        the serving loop never pays for a re-embed."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ocvf-rollout")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                progressed = self.run_stage_step()
                if self.caught_up and self._phase == "staging":
                    self._set_phase("parity" if self.parity is not None
                                    else "ready")
                self._drain_live()
                if (self._phase == "parity" and self.parity is not None
                        and self.parity.ok()):
                    self._set_phase("ready")
            except InjectedCrashError:
                raise  # simulated kill: the thread dies like the process
            except Exception:  # noqa: BLE001 — staging must not die silently
                logger.exception("rollout background step failed")
                if self.metrics is not None:
                    self.metrics.incr(mn.ROLLOUT_STAGE_ERRORS)
                progressed = False
            if not progressed:
                self._stop.wait(timeout=0.02)

    # ---- dual-score parity over live traffic ----

    def offer_live(self, frame: np.ndarray, faces: List[Dict[str, Any]]) -> None:
        """Publish-path hook (``RecognizerService._publish``): sample the
        best detected face crop, rate-limited, COPIED (the frame lives in
        a recycled staging buffer), onto the rollout thread's queue.
        Cheap and non-blocking by contract — the hot path pays one clock
        read in the common (not-due) case."""
        if self.parity is None or not faces:
            return
        now = time.monotonic()
        if now - self._last_live_t < self._live_interval_s:
            return
        self._last_live_t = now
        best = max(faces, key=lambda f: f.get("detection_score", 0.0))
        x0, y0, x1, y1 = (int(round(v)) for v in best["box"])
        h, w = frame.shape[:2]
        y0, y1 = max(0, y0), min(h, y1)
        x0, x1 = max(0, x0), min(w, x1)
        if y1 - y0 < 4 or x1 - x0 < 4:
            return
        with self._live_lock:
            self._live_q.append(frame[y0:y1, x0:x1].copy())

    def _drain_live(self) -> None:
        with self._live_lock:
            crops = list(self._live_q)
            self._live_q.clear()
        if crops:
            self.score_parity(crops)

    def score_parity(self, crops) -> int:
        """Score query crops through both embedders (the rollout thread's
        path for live samples; tests and the chaos harness call it
        directly with synthetic traffic). No-op (0) until the stage has
        rows to match against."""
        if self.parity is None or self.stage.watermark == 0:
            return 0
        if self.face_size is not None:
            from opencv_facerecognizer_tpu.ops import image as image_ops

            crops = [np.asarray(image_ops.resize(np.asarray(c, np.float32),
                                                 self.face_size))
                     for c in crops]
        batch = np.stack([np.asarray(c, np.float32) for c in crops])
        old_emb, old_lab, _val, size = self.gallery.snapshot()
        new_rows, new_labels = self.stage.arrays()
        return self.parity.score(batch, old_emb[:size], old_lab[:size],
                                 new_rows, new_labels)

    def parity_ok(self) -> bool:
        return self.parity is not None and self.parity.ok()

    # ---- the gated atomic cutover ----

    def cutover(self, force: bool = False) -> int:
        """Atomic fleet cutover (module docstring): gate -> locked
        finalize (stage the enrollment delta durably) -> WAL fence ->
        epoch-fenced install -> forced checkpoint. Returns the fence
        record's WAL sequence. Raises ``RolloutGateError`` when the stage
        is far behind or the parity window has not cleared its threshold
        (``force`` overrides both — and is required when no parity
        embedders were wired)."""
        if not force:
            reasons = []
            if not self.caught_up:
                reasons.append(f"stage watermark {self.stage.watermark} < "
                               f"gallery size {int(self.gallery.size)}")
            if self.parity is None:
                reasons.append("no parity window wired (old/new embed fns)")
            elif not self.parity.ok():
                reasons.append(
                    f"parity gate not met: agreement "
                    f"{self.parity.agreement:.4f} over "
                    f"{self.parity.samples} samples (need >= "
                    f"{self.parity.threshold:g} over >= "
                    f"{self.parity.min_samples})")
            if reasons:
                if self.metrics is not None:
                    self.metrics.incr(mn.ROLLOUT_CUTOVER_BLOCKED)
                raise RolloutGateError("cutover refused: "
                                       + "; ".join(reasons))
        # Stop the background staging/parity thread BEFORE the locked
        # finalize: ReEmbedStage is single-writer by contract, and the
        # thread's run_stage_step would otherwise race build()'s own
        # stage_chunk/arrays on the chunk map (and could even re-create a
        # headerless stage file after discard()).
        self.stop()

        def build() -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
            # Runs under the lifecycle's enroll lock: no enrollment can
            # land between the delta re-embed and the install, so the
            # staged set covers EXACTLY the gallery being swapped.
            emb, lab, _val, size = self.gallery.snapshot()
            while self.stage.watermark < size:
                start = self.stage.watermark
                end = min(size, start + self.chunk_rows)
                rows = _l2norm(self._reembed(emb[start:end], start))
                self.stage.stage_chunk(start, rows, lab[start:end])
            new_emb, new_lab = self.stage.arrays()
            capacity = max(int(self.gallery.capacity), size)
            emb_full = np.zeros((capacity, self.stage.dim), np.float32)
            emb_full[:size] = new_emb[:size]
            lab_full = np.full((capacity,),
                               int(getattr(self.gallery, "labels_pad", -1)),
                               np.int32)
            lab_full[:size] = new_lab[:size]
            val_full = np.zeros((capacity,), bool)
            val_full[:size] = True
            return emb_full, lab_full, val_full, size

        self._set_phase("cutover")
        seq = self.state.perform_cutover(self.to_version, build)
        # Forced checkpoint: the cutover is fence-durable already (a crash
        # here recovers INTO the new version from the stage); the
        # checkpoint makes it cheap (no stage replay) and lets replicas
        # re-anchor. The stage file is discarded only once it lands.
        if self.state.checkpoint_now(wait=True):
            self.stage.discard()
        else:
            self.state.maybe_checkpoint(force=True)
            logger.warning(
                "post-cutover checkpoint did not land; the stage file is "
                "retained and the forced-checkpoint latch will retry")
        self._set_phase("done")
        return seq

    def rollback(self, reembed_fn: Callable[[np.ndarray], np.ndarray],
                 **overrides) -> "RolloutCoordinator":
        """Rollback is the SAME mechanism pointed at the prior space: a
        fresh coordinator whose ``reembed_fn`` maps the rolled-out rows
        back into the previous embedder's space, at the next monotonic
        version (versions never reuse numbers — the fence stays
        unambiguous in the WAL). Stage -> parity -> cutover apply
        unchanged; the returned coordinator is NOT started."""
        if self.metrics is not None:
            self.metrics.incr(mn.ROLLOUT_ROLLBACKS)
        kwargs: Dict[str, Any] = dict(
            parity_threshold=(self.parity.threshold
                              if self.parity is not None else 0.98),
            parity_min_samples=(self.parity.min_samples
                                if self.parity is not None else 32),
            parity_window=self._parity_window,
            chunk_rows=self.chunk_rows, metrics=self.metrics,
            tracer=self.tracer, face_size=self.face_size,
            live_sample_interval_s=self._live_interval_s,
            fault_injector=self._fault_injector)
        if self.parity is not None:
            # The parity pair swaps roles: the NEW serving embedder is the
            # one being rolled back FROM.
            kwargs["old_embed_fn"] = self.parity.new_embed_fn
            kwargs["new_embed_fn"] = self.parity.old_embed_fn
        kwargs.update(overrides)
        return RolloutCoordinator(self.state, self.gallery, reembed_fn,
                                  self.to_version + 1, **kwargs)

    # ---- observability ----

    def status(self) -> Dict[str, Any]:
        """JSON-able snapshot for ``GET /rollout`` and the chaos report."""
        out = {
            "phase": self._phase,
            "from_version": self.from_version,
            "to_version": self.to_version,
            "staged_rows": self.stage.watermark,
            "total_rows": int(self.gallery.size),
            "caught_up": self.caught_up,
            "stage_resumed": self.stage.resumed,
            "parity": None,
        }
        if self.parity is not None:
            out["parity"] = {
                "samples": self.parity.samples,
                "agreement": round(self.parity.agreement, 4),
                "threshold": self.parity.threshold,
                "min_samples": self.parity.min_samples,
                "ok": self.parity.ok(),
            }
        return out
