"""Recognizer service: the reference's recognizer node rebuilt
(SURVEY.md §3.3: "enqueue frame -> batcher -> one sharded
detect->align->embed->match call per batch").

Flow: connector frames -> FrameBatcher -> RecognitionPipeline (one fused
device call per batch) -> async-readback queue -> result messages on the
connector.

Two hard-won design points (both measured on this box, see
parallel/gallery.py for the sibling finding):
- **Never block on device results in the loop.** On the axon backend the
  first synchronous device->host readback drops the process into a ~100 ms
  poll mode. The service therefore dispatches a batch, calls
  ``copy_to_host_async`` on the outputs, parks them in an in-flight queue,
  and only materializes results whose transfer already completed
  (``is_ready``) — the host pipeline SURVEY.md §7 called for.
- **Reload without drop** (SURVEY.md §5.3): retraining builds a NEW gallery
  (or pipeline) off-thread; ``reload_gallery`` swaps the reference between
  batches. In-flight batches keep the arrays they captured.

The interactive-trainer protocol (SURVEY.md §2.1 "Interactive trainer")
rides the same connector: an ``enroll`` command captures the next N detected
face crops for a subject, embeds them, and installs the grown gallery.

Steady-state failure handling (the round-4 outage, generalized — see
``runtime.resilience``): a dispatch failure retries with exponential
backoff (transient/outage-shaped errors only; a poisoned batch is abandoned
immediately), a readback that outlives its per-batch deadline is
dead-lettered and the loop keeps serving, and N consecutive dispatch
failures flip the service into degraded mode with a ``STATUS_TOPIC``
announcement (optionally probing the backend via ``utils.backend_probe``
and invoking a CPU-fallback hook when it is dead). A crash that escapes the
loop body sets ``loop_crashed`` for ``resilience.ServiceSupervisor`` to
restart with the last-known-good gallery. ``runtime.faults.FaultInjector``
installs at every one of these boundaries to make the whole story testable.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
from opencv_facerecognizer_tpu.runtime.batcher import FrameBatcher
from opencv_facerecognizer_tpu.runtime.connector import (
    MiddlewareConnector,
    decode_frame,
)
from opencv_facerecognizer_tpu.runtime.resilience import (
    ResiliencePolicy,
    is_transient_error,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics

FRAME_TOPIC = "ocvfacerec/frames"
RESULT_TOPIC = "ocvfacerec/results"
CONTROL_TOPIC = "ocvfacerec/control"
STATUS_TOPIC = "ocvfacerec/status"


@dataclass
class _Enrolment:
    subject_name: str
    needed: int
    crops: List[np.ndarray] = field(default_factory=list)


class RecognizerService:
    def __init__(
        self,
        pipeline: RecognitionPipeline,
        connector: MiddlewareConnector,
        batch_size: int = 8,
        frame_shape: Optional[tuple] = None,
        flush_timeout: float = 0.05,
        # Backpressure knob: beyond this many undrained batches the loop
        # BLOCKS on the oldest readback before dispatching more. Keep it
        # shallow — each in-flight batch is a full device round-trip of
        # latency debt (~300 ms on a tunneled backend); a deep queue turns
        # into seconds of backlog while the batcher keeps accepting frames.
        inflight_depth: int = 4,
        similarity_threshold: float = 0.3,
        subject_names: Optional[List[str]] = None,
        metrics: Optional[Metrics] = None,
        # uint8 ships frames host->device 4x cheaper (cast to f32 happens
        # in-graph); right whenever the source is 8-bit camera frames.
        transfer_dtype=np.float32,
        # Steady-state failure handling (runtime.resilience docstring).
        resilience: Optional[ResiliencePolicy] = None,
        # Chaos hook (runtime.faults): installs at connector receive,
        # batcher put, device dispatch, and async readback. None in
        # production — every hook site is a no-op without it.
        fault_injector=None,
        # Degraded-mode backend check, injectable for tests. Default runs
        # utils.backend_probe's bounded subprocess probe (allow_cpu=False:
        # "usable" means the accelerator answers, not a CPU fallback).
        backend_probe_fn: Optional[Callable[[], tuple]] = None,
        # Called with this service when degraded mode finds the backend
        # dead: the app wires its CPU re-initialization here (rebuild the
        # pipeline on host devices) so a dead accelerator degrades the
        # job instead of wedging it.
        cpu_fallback: Optional[Callable[["RecognizerService"], None]] = None,
    ):
        self.pipeline = pipeline
        self.connector = connector
        self.similarity_threshold = float(similarity_threshold)
        self.subject_names = list(subject_names) if subject_names else []
        self.metrics = metrics or Metrics()
        self.resilience = resilience or ResiliencePolicy()
        self._faults = fault_injector
        self._backend_probe_fn = backend_probe_fn
        self._cpu_fallback = cpu_fallback
        if frame_shape is None:
            raise ValueError("frame_shape (H, W) is required (static device shapes)")
        self.batcher = FrameBatcher(batch_size, frame_shape, flush_timeout,
                                    dtype=transfer_dtype,
                                    metrics=self.metrics,
                                    fault_injector=fault_injector)
        self.inflight_depth = int(inflight_depth)
        self._inflight: deque = deque()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._crashed = False
        self._consecutive_dispatch_failures = 0
        self._degraded = False
        # Completion counter paired with batcher.delivered_batches: a batch
        # counts as completed only once PUBLISHED (or abandoned on dispatch
        # failure), so drain() sees every popped batch through its whole
        # lifetime — there is no window where a batch in hand is invisible
        # (round-2 advisor #3: a bare _dispatching flag had one between
        # get_batch() and the flag write).
        self._completed_batches = 0
        self._enrolment: Optional[_Enrolment] = None
        self._enrol_lock = threading.Lock()
        # Called (no args, best-effort) after every COMMITTED gallery
        # change — a finished enrolment, a reload_gallery swap. This is a
        # direct callback, not a status-topic subscription, deliberately:
        # wire connectors (JSONL/socket) publish outbound only and never
        # dispatch their own publishes to local subscribers, so a
        # supervisor listening on STATUS_TOPIC would never hear commits in
        # production. ServiceSupervisor registers its checkpoint here.
        self.commit_hooks: List[Callable[[], None]] = []

        # Enrolment embeds ride a FIXED-size padded chunk: one compiled
        # shape, warmed at start(), so an enroll command never triggers a
        # mid-serving XLA compile (measured ~85 s stall on this backend).
        self._enrol_chunk = 8

        def _embed_chunk(params, crops):
            from opencv_facerecognizer_tpu.models.embedder import normalize_faces

            return self.pipeline.embed_net.apply(
                {"params": params},
                normalize_faces(crops, self.pipeline.face_size),
            )

        import jax

        self._embed_chunk = jax.jit(_embed_chunk)
        # Placement override for the enrolment graph. None = default
        # backend. rebuild_pipeline_on_cpu pins this to the CPU device it
        # rebuilt on: the bare jit above takes uncommitted numpy inputs
        # and would otherwise keep dispatching enrolment embeds on the
        # dead accelerator after a CPU fallback.
        self._embed_device = None

        connector.subscribe(FRAME_TOPIC, self._on_frame)
        connector.subscribe(CONTROL_TOPIC, self._on_control)

    def _run_embed_chunk(self, params, crops):
        """One fixed-size enrolment embed, honoring ``_embed_device``
        (``jax.default_device`` participates in the jit cache key, so the
        retargeted call compiles for — and runs on — the pinned device)."""
        import contextlib

        import jax

        ctx = (jax.default_device(self._embed_device)
               if self._embed_device is not None else contextlib.nullcontext())
        with ctx:
            return self._embed_chunk(params, crops)

    # ---- connector handlers (dispatch thread; keep cheap) ----

    def _on_frame(self, topic: str, message: Dict[str, Any]) -> None:
        # Connector-receive fault boundary: the injector may drop,
        # duplicate, or corrupt the delivery (runtime.faults).
        messages = ([message] if self._faults is None
                    else self._faults.on_receive(message))
        for msg in messages:
            try:
                frame = decode_frame(msg) if "__frame__" in msg else np.asarray(
                    msg["frame"]
                )
            except Exception:
                self.metrics.incr("frames_malformed")
                continue
            if not self.batcher.put(frame, meta=msg.get("meta")):
                self.metrics.incr("frames_dropped")

    def _on_control(self, topic: str, message: Dict[str, Any]) -> None:
        cmd = message.get("cmd")
        if cmd == "enroll":
            name = str(message.get("subject", f"subject_{len(self.subject_names)}"))
            count = int(message.get("count", 5))
            with self._enrol_lock:
                # The label is assigned (and subject_names grown) only when
                # _finish_enrolment succeeds — an abandoned or superseded
                # enrolment must not leave a name with zero gallery rows.
                self._enrolment = _Enrolment(name, count)
            self.connector.publish(STATUS_TOPIC, {"status": "enrolling", "subject": name,
                                                  "count": count})
        elif cmd == "stats":
            self.connector.publish(STATUS_TOPIC, {"status": "stats",
                                                  **self.metrics.summary(),
                                                  **self.batcher.stats,
                                                  "degraded": self._degraded,
                                                  "gallery_size": self.pipeline.gallery.size})

    # ---- lifecycle ----

    def start(self, warmup: bool = True) -> None:
        if self._thread is not None:
            return
        if warmup:
            self.warmup()
        # Install the dispatch fault boundary on the pipeline AFTER warmup:
        # the warmup compile must never consume a scripted chaos fault (or
        # randomly fail under soak rates) — only real serving batches cross
        # the boundary. stop() uninstalls, so a shared pipeline leaks no
        # injector into the next service built on it.
        if self._faults is not None:
            self.pipeline.fault_injector = self._faults
        self._running = True
        self._crashed = False
        self.connector.start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def warmup(self) -> None:
        """Compile the serving + enrolment graphs before frames arrive, so
        the first batch and the first enroll command pay no compile stall."""
        t0 = time.perf_counter()
        zeros = np.zeros((self.batcher.batch_size, *self.batcher.frame_shape),
                         self.batcher.dtype)
        packed = self.pipeline.recognize_batch_packed(zeros)
        chunk = np.zeros((self._enrol_chunk, *self.pipeline.face_size), np.float32)
        emb = self._run_embed_chunk(self.pipeline.embed_params, chunk)
        for arr in (packed, emb):
            arr.block_until_ready() if hasattr(arr, "block_until_ready") else None
        self.metrics.observe("warmup", time.perf_counter() - t0)

    def drain(self, timeout: float = 120.0) -> bool:
        """Block until every accepted frame has been batched, computed, AND
        published (or timeout). Call at end-of-stream BEFORE stop() —
        stop() tears the loop down promptly and discards whatever is still
        queued, which is right for Ctrl-C but wrong for a finite stream."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # delivered == completed covers popped-but-undispatched batches,
            # the in-flight queue, AND publish-in-progress (completed is
            # bumped only after _publish returns).
            if (self.batcher.pending == 0
                    and self.batcher.delivered_batches == self._completed_batches):
                return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._running = False
        self.batcher.close()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None
        if thread is None or not thread.is_alive():
            # Final materialize only once the loop thread is truly gone —
            # two threads force-draining the same deque could pair one
            # batch's results with another's metadata. A loop thread still
            # alive here is bounded-waiting on a readback deadline and
            # will finish its own force drain.
            self._drain(force=True)
        if self._faults is not None and getattr(
                self.pipeline, "fault_injector", None) is self._faults:
            self.pipeline.fault_injector = None
        self.connector.stop()

    # ---- the serving loop ----

    @property
    def loop_crashed(self) -> bool:
        """True when an exception escaped the loop body and killed the
        serving thread (``ServiceSupervisor`` watches this flag)."""
        return self._crashed

    def restart_loop(self) -> None:
        """Restart a crashed serving loop (supervisor path). Re-syncs the
        completed-batch accounting first: a crash between popping a batch
        and publishing it would otherwise leave ``drain()`` waiting forever
        for a completion that can no longer happen."""
        if not self._running or self._thread is None:
            return
        if self._thread.is_alive():
            return  # not actually crashed
        self._completed_batches = (self.batcher.delivered_batches
                                   - len(self._inflight))
        self._crashed = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        try:
            self._serve_loop()
        except Exception:  # noqa: BLE001 — flag the crash for the supervisor
            logging.getLogger(__name__).exception("serving loop crashed")
            self.metrics.incr("loop_crashes")
            self._crashed = True
            self._publish_status({"status": "crashed"})

    def _serve_loop(self) -> None:
        while self._running:
            batch = self.batcher.get_batch(block=True)
            if batch is None:
                if not self._running:
                    break
                self._drain()
                continue
            frames, metas, count = batch.frames, batch.metas, batch.count
            t0 = time.perf_counter()
            # Queue-wait: frame enqueue -> batch pop. The batching-delay
            # term of the end-to-end latency decomposition (flush window +
            # waiting for batch_size peers), measured per frame.
            now_mono = time.monotonic()
            for ts in batch.enqueue_ts:
                self.metrics.observe("queue_wait", now_mono - ts)
            packed = self._dispatch_with_retry(frames)
            if packed is None:
                # Retries exhausted or the error was permanent (poisoned
                # batch): abandoned, not published — but still completed
                # for drain() accounting.
                self._completed_batches += 1
                continue
            # Host-side dispatch cost (H2D + trace-cache hit + async enqueue
            # — never device compute, which is async from here).
            t_disp = time.perf_counter()
            self.metrics.observe("dispatch", t_disp - t0)
            deadline = time.monotonic() + self.resilience.readback_deadline_s
            self._inflight.append((packed, frames, metas, count, t0, t_disp,
                                   deadline))
            self.metrics.incr("batches_dispatched")
            self.metrics.incr("frames_processed", count)
            self._drain()
        self._drain(force=True)

    def _dispatch_with_retry(self, frames) -> Optional[Any]:
        """One batch through the device, honoring the resilience policy:
        transient failures retry with exponential backoff (draining
        readbacks while waiting), permanent ones abandon immediately, and
        ``degraded_after`` consecutive failed attempts publish degraded
        mode. Returns the dispatched (async) output, or None when the
        batch is abandoned (``batches_failed``)."""
        policy = self.resilience
        attempt = 0
        while True:
            try:
                # Packed path: ONE output array -> one D2H readback per
                # batch (a tunneled backend charges ~100 ms per blocking
                # readback; five separate arrays measured 5x slower).
                packed = self.pipeline.recognize_batch_packed(frames)
                packed.copy_to_host_async()
            except Exception as exc:  # noqa: BLE001 — classified below
                self.metrics.incr("dispatch_failures")
                self._consecutive_dispatch_failures += 1
                if (self._consecutive_dispatch_failures >= policy.degraded_after
                        and not self._degraded):
                    self._enter_degraded(exc)
                transient = is_transient_error(exc)
                if not transient or attempt >= policy.dispatch_retries:
                    logging.getLogger(__name__).exception(
                        "recognition batch abandoned (%s, attempt %d)",
                        "transient" if transient else "permanent", attempt)
                    self.metrics.incr("batches_failed")
                    return None
                self.metrics.incr("dispatch_retries")
                self._backoff_wait(policy.backoff(attempt))
                attempt += 1
                if not self._running:
                    self.metrics.incr("batches_failed")
                    return None
                continue
            if self._consecutive_dispatch_failures:
                self._consecutive_dispatch_failures = 0
            if self._degraded:
                self._exit_degraded()
            # Async-readback fault boundary (runtime.faults): may wrap the
            # output in a never-ready proxy — the hang-mode outage.
            if self._faults is not None:
                packed = self._faults.on_readback(packed)
            return packed

    def _backoff_wait(self, seconds: float) -> None:
        """Sleep in small slices, still draining in-flight readbacks (a
        retry storm must not let completed batches rot past their result
        consumers) and bailing promptly on stop()."""
        deadline = time.monotonic() + seconds
        while self._running and time.monotonic() < deadline:
            self._drain()
            time.sleep(min(0.01, max(0.0, deadline - time.monotonic())))

    # ---- degraded mode ----

    def _enter_degraded(self, exc: BaseException) -> None:
        self._degraded = True
        self.metrics.incr("degraded_transitions")
        status = {
            "status": "degraded",
            "consecutive_failures": self._consecutive_dispatch_failures,
            "error": repr(exc),
        }
        if self.resilience.probe_backend_on_degraded:
            usable, reason = self._probe_backend()
            status["backend_usable"] = usable
            status["backend_reason"] = reason
            if not usable and self._cpu_fallback is not None:
                try:
                    self._cpu_fallback(self)
                    self.metrics.incr("cpu_fallbacks")
                    status["cpu_fallback"] = True
                except Exception:  # noqa: BLE001 — fallback is best-effort
                    logging.getLogger(__name__).exception("cpu fallback failed")
                    status["cpu_fallback"] = False
        self._publish_status(status)

    def _exit_degraded(self) -> None:
        self._degraded = False
        self.metrics.incr("degraded_recoveries")
        self._publish_status({"status": "recovered"})

    def _publish_status(self, status: Dict[str, Any]) -> None:
        """Status publishes run on the serving thread and subscribers are
        arbitrary app code — a raising status consumer must degrade to a
        logged error, never crash the loop it is reporting on."""
        try:
            self.connector.publish(STATUS_TOPIC, status)
        except Exception:  # noqa: BLE001 — transport/subscriber may be down
            logging.getLogger(__name__).exception("status publish failed")

    def _probe_backend(self) -> tuple:
        """Bounded verdict on the accelerator (never hangs): the injected
        fn for tests, else utils.backend_probe's subprocess probe with
        allow_cpu=False — a silent JAX CPU fallback must read as "backend
        dead", not "healthy", or the CPU-fallback hook never fires."""
        if self._backend_probe_fn is not None:
            return self._backend_probe_fn()
        from opencv_facerecognizer_tpu.utils.backend_probe import (
            probe_for_recovery,
        )

        return probe_for_recovery(timeout_s=self.resilience.probe_timeout_s)

    def _dead_letter(self, count: int) -> None:
        """Abandon a batch whose readback outlived its deadline: counted,
        announced, completed — never blocked on (SURVEY.md §5.3: an
        unhealthy accelerator degrades the job, never wedges it)."""
        self.metrics.incr("batches_dead_lettered")
        self.metrics.incr("frames_dead_lettered", count)
        self._completed_batches += 1
        self._publish_status({"status": "dead_letter", "frames": count})

    @staticmethod
    def _is_ready(packed) -> bool:
        """Non-blocking readiness; backends without ``is_ready`` report
        ready and fall back to the blocking materialize (old behavior)."""
        try:
            return bool(packed.is_ready())
        except (AttributeError, NotImplementedError):
            return True

    def _drain(self, force: bool = False) -> None:
        """Materialize finished batches. A not-ready head batch past its
        readback deadline is dead-lettered; when over depth (or forced) the
        wait for the head is a bounded is_ready poll capped by that same
        deadline — never an unbounded blocking readback a hang-mode outage
        could wedge."""
        while self._inflight:
            packed, frames, metas, count, t0, t_disp, deadline = self._inflight[0]
            ready = self._is_ready(packed)
            if not ready:
                if time.monotonic() >= deadline:
                    self._inflight.popleft()
                    self._dead_letter(count)
                    continue
                if not (force or len(self._inflight) > self.inflight_depth):
                    break
                # Over depth / forced: poll until ready or deadline. The
                # poll IS the readback wait — it lands in ready_wait below.
                while not ready and time.monotonic() < deadline:
                    time.sleep(0.005)
                    ready = self._is_ready(packed)
                if not ready:
                    self._inflight.popleft()
                    self._dead_letter(count)
                    continue
            self._inflight.popleft()
            # Materialize BEFORE stamping ready_wait: on the blocking
            # (over-depth/forced) path np.asarray is the readback itself and
            # must land in ready_wait, not in publish.
            arr = np.asarray(packed)
            # dispatch-END -> readback-complete (measured from t_disp, so
            # the host dispatch segment is not double-counted with the
            # 'dispatch' metric): device compute + D2H readback + the drain
            # loop's polling slack (on the tunneled backend the ~100 ms
            # sync-poll readback floor lands in THIS term — compare against
            # bench.py's chained-diff device ms/batch to see how much is
            # tunnel vs chip).
            self.metrics.observe("ready_wait", time.perf_counter() - t_disp)
            t_pub = time.perf_counter()
            self._publish(arr, frames, metas, count)
            self._completed_batches += 1
            self.metrics.observe("publish", time.perf_counter() - t_pub)
            self.metrics.observe("batch_latency", time.perf_counter() - t0)

    def _publish(self, packed, frames, metas, count) -> None:
        from opencv_facerecognizer_tpu.parallel.pipeline import unpack_result

        result = unpack_result(np.asarray(packed), self.pipeline.top_k)  # no-op if already host
        boxes = result.boxes
        det_scores = result.det_scores
        valid = result.valid
        labels = result.labels
        sims = result.similarities
        for i in range(count):
            faces = []
            for j in range(boxes.shape[1]):
                if not valid[i, j]:
                    continue
                sim = float(sims[i, j, 0])
                label = int(labels[i, j, 0])
                known = sim >= self.similarity_threshold and label >= 0
                name = (
                    self.subject_names[label]
                    if known and label < len(self.subject_names)
                    else ("unknown" if not known else str(label))
                )
                y0, x0, y1, x1 = (float(v) for v in boxes[i, j])
                faces.append({
                    "box": [x0, y0, x1, y1],  # x-first, like the reference API
                    "detection_score": float(det_scores[i, j]),
                    "label": label if known else -1,
                    "name": name,
                    "similarity": sim,
                })
            self._maybe_collect_enrolment(frames[i], faces)
            self.connector.publish(RESULT_TOPIC, {"meta": metas[i], "faces": faces})
            self.metrics.incr("faces_found", len(faces))

    # ---- enrolment (interactive-trainer protocol) ----

    def _maybe_collect_enrolment(self, frame: np.ndarray, faces: List[dict]) -> None:
        with self._enrol_lock:
            enrolment = self._enrolment
        if enrolment is None or not faces:
            return
        best = max(faces, key=lambda f: f["detection_score"])
        x0, y0, x1, y1 = (int(round(v)) for v in best["box"])
        h, w = frame.shape
        y0, y1 = max(0, y0), min(h, y1)
        x0, x1 = max(0, x0), min(w, x1)
        if y1 - y0 < 4 or x1 - x0 < 4:
            return
        enrolment.crops.append(frame[y0:y1, x0:x1])
        if len(enrolment.crops) >= enrolment.needed:
            with self._enrol_lock:
                self._enrolment = None
            # Off the serving thread: the embed + gallery install must not
            # stall frame batches (reload-without-drop, SURVEY.md §5.3).
            threading.Thread(
                target=self._finish_enrolment, args=(enrolment,), daemon=True
            ).start()

    def _finish_enrolment(self, enrolment: _Enrolment) -> None:
        from opencv_facerecognizer_tpu.ops import image as image_ops

        face_size = self.pipeline.face_size
        crops = np.stack(
            [np.asarray(image_ops.resize(c, face_size)) for c in enrolment.crops]
        )
        # Embed in fixed-size padded chunks (pre-compiled in warmup()).
        embeddings = []
        for start in range(0, len(crops), self._enrol_chunk):
            part = crops[start : start + self._enrol_chunk]
            padded = np.zeros((self._enrol_chunk, *face_size), np.float32)
            padded[: len(part)] = part
            emb = np.array(self._run_embed_chunk(self.pipeline.embed_params,
                                                 padded))
            embeddings.append(emb[: len(part)])
        emb = np.concatenate(embeddings)
        with self._enrol_lock:
            if enrolment.subject_name in self.subject_names:
                label = self.subject_names.index(enrolment.subject_name)
            else:
                label = len(self.subject_names)
                self.subject_names.append(enrolment.subject_name)
        before_grow = self.pipeline.gallery.grow_count
        try:
            self.pipeline.gallery.add(emb, np.full(len(emb), label, np.int32))
            grown = self.pipeline.gallery.grow_count - before_grow
            if grown:
                # Auto-grow saved the enrolment but forced a recompile-sized
                # stall on the next match — surface it so operators pre-size.
                self.metrics.incr("gallery_grown", grown)
        except Exception:
            # Roll back a name we just reserved: the gallery has no rows
            # for it, so leaving it would skew label->name indices.
            with self._enrol_lock:
                if (label == len(self.subject_names) - 1
                        and self.subject_names[label] == enrolment.subject_name):
                    self.subject_names.pop()
            raise
        self.metrics.incr("subjects_enrolled")
        self.connector.publish(
            STATUS_TOPIC,
            {
                "status": "enrolled",
                "subject": enrolment.subject_name,
                "label": label,
                "gallery_size": self.pipeline.gallery.size,
            },
        )
        self._run_commit_hooks()

    # ---- reload without drop (SURVEY.md §5.3) ----

    def reload_gallery(self, new_gallery) -> None:
        """Swap in a rebuilt gallery between batches (double-buffered)."""
        self.pipeline.gallery.swap_from(new_gallery)
        self.connector.publish(STATUS_TOPIC, {"status": "reloaded",
                                              "gallery_size": self.pipeline.gallery.size})
        self._run_commit_hooks()

    def _run_commit_hooks(self) -> None:
        """Notify commit watchers (see ``commit_hooks``); a raising hook
        must not kill the enrolment worker or the reload caller."""
        for hook in list(self.commit_hooks):
            try:
                hook()
            except Exception:  # noqa: BLE001 — watcher bugs stay theirs
                logging.getLogger(__name__).exception("commit hook failed")
