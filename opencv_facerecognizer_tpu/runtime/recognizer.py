"""Recognizer service: the reference's recognizer node rebuilt
(SURVEY.md §3.3: "enqueue frame -> batcher -> one sharded
detect->align->embed->match call per batch").

Flow: connector frames -> FrameBatcher -> RecognitionPipeline (one fused
device call per batch) -> async-readback queue -> result messages on the
connector.

Two hard-won design points (both measured on this box, see
parallel/gallery.py for the sibling finding):
- **Never block on device results in the loop.** On the axon backend the
  first synchronous device->host readback drops the process into a ~100 ms
  poll mode. The service therefore dispatches a batch, calls
  ``copy_to_host_async`` on the outputs, parks them in an in-flight queue,
  and only materializes results whose transfer already completed
  (``is_ready``) — the host pipeline SURVEY.md §7 called for.
- **Reload without drop** (SURVEY.md §5.3): retraining builds a NEW gallery
  (or pipeline) off-thread; ``reload_gallery`` swaps the reference between
  batches. In-flight batches keep the arrays they captured.

The interactive-trainer protocol (SURVEY.md §2.1 "Interactive trainer")
rides the same connector: an ``enroll`` command captures the next N detected
face crops for a subject, embeds them, and installs the grown gallery.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
from opencv_facerecognizer_tpu.runtime.batcher import FrameBatcher
from opencv_facerecognizer_tpu.runtime.connector import (
    MiddlewareConnector,
    decode_frame,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics

FRAME_TOPIC = "ocvfacerec/frames"
RESULT_TOPIC = "ocvfacerec/results"
CONTROL_TOPIC = "ocvfacerec/control"
STATUS_TOPIC = "ocvfacerec/status"


@dataclass
class _Enrolment:
    subject_name: str
    needed: int
    crops: List[np.ndarray] = field(default_factory=list)


class RecognizerService:
    def __init__(
        self,
        pipeline: RecognitionPipeline,
        connector: MiddlewareConnector,
        batch_size: int = 8,
        frame_shape: Optional[tuple] = None,
        flush_timeout: float = 0.05,
        # Backpressure knob: beyond this many undrained batches the loop
        # BLOCKS on the oldest readback before dispatching more. Keep it
        # shallow — each in-flight batch is a full device round-trip of
        # latency debt (~300 ms on a tunneled backend); a deep queue turns
        # into seconds of backlog while the batcher keeps accepting frames.
        inflight_depth: int = 4,
        similarity_threshold: float = 0.3,
        subject_names: Optional[List[str]] = None,
        metrics: Optional[Metrics] = None,
        # uint8 ships frames host->device 4x cheaper (cast to f32 happens
        # in-graph); right whenever the source is 8-bit camera frames.
        transfer_dtype=np.float32,
    ):
        self.pipeline = pipeline
        self.connector = connector
        self.similarity_threshold = float(similarity_threshold)
        self.subject_names = list(subject_names) if subject_names else []
        self.metrics = metrics or Metrics()
        if frame_shape is None:
            raise ValueError("frame_shape (H, W) is required (static device shapes)")
        self.batcher = FrameBatcher(batch_size, frame_shape, flush_timeout,
                                    dtype=transfer_dtype)
        self.inflight_depth = int(inflight_depth)
        self._inflight: deque = deque()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # Completion counter paired with batcher.delivered_batches: a batch
        # counts as completed only once PUBLISHED (or abandoned on dispatch
        # failure), so drain() sees every popped batch through its whole
        # lifetime — there is no window where a batch in hand is invisible
        # (round-2 advisor #3: a bare _dispatching flag had one between
        # get_batch() and the flag write).
        self._completed_batches = 0
        self._enrolment: Optional[_Enrolment] = None
        self._enrol_lock = threading.Lock()

        # Enrolment embeds ride a FIXED-size padded chunk: one compiled
        # shape, warmed at start(), so an enroll command never triggers a
        # mid-serving XLA compile (measured ~85 s stall on this backend).
        self._enrol_chunk = 8

        def _embed_chunk(params, crops):
            from opencv_facerecognizer_tpu.models.embedder import normalize_faces

            return self.pipeline.embed_net.apply(
                {"params": params},
                normalize_faces(crops, self.pipeline.face_size),
            )

        import jax

        self._embed_chunk = jax.jit(_embed_chunk)

        connector.subscribe(FRAME_TOPIC, self._on_frame)
        connector.subscribe(CONTROL_TOPIC, self._on_control)

    # ---- connector handlers (dispatch thread; keep cheap) ----

    def _on_frame(self, topic: str, message: Dict[str, Any]) -> None:
        try:
            frame = decode_frame(message) if "__frame__" in message else np.asarray(
                message["frame"]
            )
        except Exception:
            self.metrics.incr("frames_malformed")
            return
        if not self.batcher.put(frame, meta=message.get("meta")):
            self.metrics.incr("frames_dropped")

    def _on_control(self, topic: str, message: Dict[str, Any]) -> None:
        cmd = message.get("cmd")
        if cmd == "enroll":
            name = str(message.get("subject", f"subject_{len(self.subject_names)}"))
            count = int(message.get("count", 5))
            with self._enrol_lock:
                # The label is assigned (and subject_names grown) only when
                # _finish_enrolment succeeds — an abandoned or superseded
                # enrolment must not leave a name with zero gallery rows.
                self._enrolment = _Enrolment(name, count)
            self.connector.publish(STATUS_TOPIC, {"status": "enrolling", "subject": name,
                                                  "count": count})
        elif cmd == "stats":
            self.connector.publish(STATUS_TOPIC, {"status": "stats",
                                                  **self.metrics.summary(),
                                                  **self.batcher.stats,
                                                  "gallery_size": self.pipeline.gallery.size})

    # ---- lifecycle ----

    def start(self, warmup: bool = True) -> None:
        if self._thread is not None:
            return
        if warmup:
            self.warmup()
        self._running = True
        self.connector.start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def warmup(self) -> None:
        """Compile the serving + enrolment graphs before frames arrive, so
        the first batch and the first enroll command pay no compile stall."""
        t0 = time.perf_counter()
        zeros = np.zeros((self.batcher.batch_size, *self.batcher.frame_shape),
                         self.batcher.dtype)
        packed = self.pipeline.recognize_batch_packed(zeros)
        chunk = np.zeros((self._enrol_chunk, *self.pipeline.face_size), np.float32)
        emb = self._embed_chunk(self.pipeline.embed_params, chunk)
        for arr in (packed, emb):
            arr.block_until_ready() if hasattr(arr, "block_until_ready") else None
        self.metrics.observe("warmup", time.perf_counter() - t0)

    def drain(self, timeout: float = 120.0) -> bool:
        """Block until every accepted frame has been batched, computed, AND
        published (or timeout). Call at end-of-stream BEFORE stop() —
        stop() tears the loop down promptly and discards whatever is still
        queued, which is right for Ctrl-C but wrong for a finite stream."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # delivered == completed covers popped-but-undispatched batches,
            # the in-flight queue, AND publish-in-progress (completed is
            # bumped only after _publish returns).
            if (self.batcher.pending == 0
                    and self.batcher.delivered_batches == self._completed_batches):
                return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        self._running = False
        self.batcher.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._drain(force=True)
        self.connector.stop()

    # ---- the serving loop ----

    def _loop(self) -> None:
        while self._running:
            batch = self.batcher.get_batch(block=True)
            if batch is None:
                if not self._running:
                    break
                self._drain()
                continue
            frames, metas, count = batch.frames, batch.metas, batch.count
            t0 = time.perf_counter()
            # Queue-wait: frame enqueue -> batch pop. The batching-delay
            # term of the end-to-end latency decomposition (flush window +
            # waiting for batch_size peers), measured per frame.
            now_mono = time.monotonic()
            for ts in batch.enqueue_ts:
                self.metrics.observe("queue_wait", now_mono - ts)
            try:
                # Packed path: ONE output array -> one D2H readback per
                # batch (a tunneled backend charges ~100 ms per blocking
                # readback; five separate arrays measured 5x slower).
                packed = self.pipeline.recognize_batch_packed(frames)
                packed.copy_to_host_async()
            except Exception:  # noqa: BLE001 — a bad batch must not kill serving
                logging.getLogger(__name__).exception("recognition batch failed")
                self.metrics.incr("batches_failed")
                self._completed_batches += 1  # abandoned, not published
                continue
            # Host-side dispatch cost (H2D + trace-cache hit + async enqueue
            # — never device compute, which is async from here).
            t_disp = time.perf_counter()
            self.metrics.observe("dispatch", t_disp - t0)
            self._inflight.append((packed, frames, metas, count, t0, t_disp))
            self.metrics.incr("batches_dispatched")
            self.metrics.incr("frames_processed", count)
            self._drain()
        self._drain(force=True)

    def _drain(self, force: bool = False) -> None:
        """Materialize finished batches; block only when over depth/forced."""
        while self._inflight:
            packed, frames, metas, count, t0, t_disp = self._inflight[0]
            if not (packed.is_ready() or force
                    or len(self._inflight) > self.inflight_depth):
                break
            self._inflight.popleft()
            # Materialize BEFORE stamping ready_wait: on the blocking
            # (over-depth/forced) path np.asarray is the readback itself and
            # must land in ready_wait, not in publish.
            arr = np.asarray(packed)
            # dispatch-END -> readback-complete (measured from t_disp, so
            # the host dispatch segment is not double-counted with the
            # 'dispatch' metric): device compute + D2H readback + the drain
            # loop's polling slack (on the tunneled backend the ~100 ms
            # sync-poll readback floor lands in THIS term — compare against
            # bench.py's chained-diff device ms/batch to see how much is
            # tunnel vs chip).
            self.metrics.observe("ready_wait", time.perf_counter() - t_disp)
            t_pub = time.perf_counter()
            self._publish(arr, frames, metas, count)
            self._completed_batches += 1
            self.metrics.observe("publish", time.perf_counter() - t_pub)
            self.metrics.observe("batch_latency", time.perf_counter() - t0)

    def _publish(self, packed, frames, metas, count) -> None:
        from opencv_facerecognizer_tpu.parallel.pipeline import unpack_result

        result = unpack_result(np.asarray(packed), self.pipeline.top_k)  # no-op if already host
        boxes = result.boxes
        det_scores = result.det_scores
        valid = result.valid
        labels = result.labels
        sims = result.similarities
        for i in range(count):
            faces = []
            for j in range(boxes.shape[1]):
                if not valid[i, j]:
                    continue
                sim = float(sims[i, j, 0])
                label = int(labels[i, j, 0])
                known = sim >= self.similarity_threshold and label >= 0
                name = (
                    self.subject_names[label]
                    if known and label < len(self.subject_names)
                    else ("unknown" if not known else str(label))
                )
                y0, x0, y1, x1 = (float(v) for v in boxes[i, j])
                faces.append({
                    "box": [x0, y0, x1, y1],  # x-first, like the reference API
                    "detection_score": float(det_scores[i, j]),
                    "label": label if known else -1,
                    "name": name,
                    "similarity": sim,
                })
            self._maybe_collect_enrolment(frames[i], faces)
            self.connector.publish(RESULT_TOPIC, {"meta": metas[i], "faces": faces})
            self.metrics.incr("faces_found", len(faces))

    # ---- enrolment (interactive-trainer protocol) ----

    def _maybe_collect_enrolment(self, frame: np.ndarray, faces: List[dict]) -> None:
        with self._enrol_lock:
            enrolment = self._enrolment
        if enrolment is None or not faces:
            return
        best = max(faces, key=lambda f: f["detection_score"])
        x0, y0, x1, y1 = (int(round(v)) for v in best["box"])
        h, w = frame.shape
        y0, y1 = max(0, y0), min(h, y1)
        x0, x1 = max(0, x0), min(w, x1)
        if y1 - y0 < 4 or x1 - x0 < 4:
            return
        enrolment.crops.append(frame[y0:y1, x0:x1])
        if len(enrolment.crops) >= enrolment.needed:
            with self._enrol_lock:
                self._enrolment = None
            # Off the serving thread: the embed + gallery install must not
            # stall frame batches (reload-without-drop, SURVEY.md §5.3).
            threading.Thread(
                target=self._finish_enrolment, args=(enrolment,), daemon=True
            ).start()

    def _finish_enrolment(self, enrolment: _Enrolment) -> None:
        from opencv_facerecognizer_tpu.ops import image as image_ops

        face_size = self.pipeline.face_size
        crops = np.stack(
            [np.asarray(image_ops.resize(c, face_size)) for c in enrolment.crops]
        )
        # Embed in fixed-size padded chunks (pre-compiled in warmup()).
        embeddings = []
        for start in range(0, len(crops), self._enrol_chunk):
            part = crops[start : start + self._enrol_chunk]
            padded = np.zeros((self._enrol_chunk, *face_size), np.float32)
            padded[: len(part)] = part
            emb = np.array(self._embed_chunk(self.pipeline.embed_params, padded))
            embeddings.append(emb[: len(part)])
        emb = np.concatenate(embeddings)
        with self._enrol_lock:
            if enrolment.subject_name in self.subject_names:
                label = self.subject_names.index(enrolment.subject_name)
            else:
                label = len(self.subject_names)
                self.subject_names.append(enrolment.subject_name)
        before_grow = self.pipeline.gallery.grow_count
        try:
            self.pipeline.gallery.add(emb, np.full(len(emb), label, np.int32))
            grown = self.pipeline.gallery.grow_count - before_grow
            if grown:
                # Auto-grow saved the enrolment but forced a recompile-sized
                # stall on the next match — surface it so operators pre-size.
                self.metrics.incr("gallery_grown", grown)
        except Exception:
            # Roll back a name we just reserved: the gallery has no rows
            # for it, so leaving it would skew label->name indices.
            with self._enrol_lock:
                if (label == len(self.subject_names) - 1
                        and self.subject_names[label] == enrolment.subject_name):
                    self.subject_names.pop()
            raise
        self.metrics.incr("subjects_enrolled")
        self.connector.publish(
            STATUS_TOPIC,
            {
                "status": "enrolled",
                "subject": enrolment.subject_name,
                "label": label,
                "gallery_size": self.pipeline.gallery.size,
            },
        )

    # ---- reload without drop (SURVEY.md §5.3) ----

    def reload_gallery(self, new_gallery) -> None:
        """Swap in a rebuilt gallery between batches (double-buffered)."""
        self.pipeline.gallery.swap_from(new_gallery)
        self.connector.publish(STATUS_TOPIC, {"status": "reloaded",
                                              "gallery_size": self.pipeline.gallery.size})
