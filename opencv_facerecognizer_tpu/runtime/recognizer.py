"""Recognizer service: the reference's recognizer node rebuilt
(SURVEY.md §3.3: "enqueue frame -> batcher -> one sharded
detect->align->embed->match call per batch").

Flow: connector frames -> FrameBatcher (continuous batching) ->
RecognitionPipeline (one fused device call per batch, sliced to a bucket
of the dispatch ladder) -> in-flight queue -> **readback worker** -> result
messages on the connector.

Three hard-won design points (see parallel/gallery.py for a sibling
finding):

- **The serving loop never waits on device results.** On the axon backend
  the first synchronous device->host readback drops the process into a
  ~100 ms poll mode, and even ``is_ready`` polling quantizes the loop to
  that floor. The service therefore dispatches a batch, calls
  ``copy_to_host_async`` on the output, parks it in the in-flight queue,
  and a dedicated **readback worker thread** blocks on each batch's device
  array (event-driven ``block_until_ready``, via a sacrificial blocker
  thread so the wait stays bounded by the per-batch deadline) and runs the
  publish path. Dispatch, D2H, and publish overlap; ``inflight_depth``
  slots actually pipeline. The pre-worker inline path survives as
  ``readback_worker=False`` (the fallback non-threaded mode) with its
  two poll sleeps promoted to the named knobs ``readback_poll_s`` /
  ``drain_poll_s``.
- **Bucketed dispatch cache**: a partial batch is sliced down to the
  smallest size in a fixed ``bucket_sizes`` ladder (default 8/32/128,
  filtered to the mesh's dp divisibility and capped at ``batch_size``)
  instead of always padding to the full batch. Every ladder size is
  compiled at ``warmup()``, so partial batches never trigger recompiles,
  and the staging array each batch rides in is recycled back to the
  batcher's buffer pool once its readback completes (the host-side analog
  of a donated input buffer: steady-state dispatch does zero per-batch
  allocations. True XLA buffer donation does not apply here — the inputs
  are host numpy arrays, which jit copies rather than aliases).
- **Reload without drop** (SURVEY.md §5.3): retraining builds a NEW gallery
  (or pipeline) off-thread; ``reload_gallery`` swaps the reference between
  batches. In-flight batches keep the arrays they captured.

The interactive-trainer protocol (SURVEY.md §2.1 "Interactive trainer")
rides the same connector: an ``enroll`` command captures the next N detected
face crops for a subject, embeds them, and installs the grown gallery.

Steady-state failure handling (the round-4 outage, generalized — see
``runtime.resilience``): a dispatch failure retries with exponential
backoff (transient/outage-shaped errors only; a poisoned batch is abandoned
immediately), a readback that outlives its per-batch deadline is
dead-lettered **by the readback worker** and the loop keeps serving, and N
consecutive dispatch failures flip the service into degraded mode with a
``STATUS_TOPIC`` announcement (optionally probing the backend via
``utils.backend_probe`` and invoking a CPU-fallback hook when it is dead).
A crash that escapes either serving-side thread (the dispatch loop or the
readback worker) sets ``loop_crashed`` for ``resilience.ServiceSupervisor``
to restart with the last-known-good gallery; each crash path settles its
own batch accounting first, so ``drain()`` stays solvable after a restart.
``runtime.faults.FaultInjector`` installs at every one of these boundaries
to make the whole story testable.

**Overload protection** (the client-side mirror of the resilience story —
nothing above protects the loop from its own producers):

- **Admission control** (``runtime.admission``): ``_on_frame`` consults an
  optional ``AdmissionController`` BEFORE decoding — a rate-limited or
  over-bound frame is rejected explicitly (``frames_rejected_<reason>``
  plus an aggregated ``rejected`` backpressure status on ``STATUS_TOPIC``)
  instead of silently displacing someone else's frame later. Frames carry
  an optional ``priority`` ("interactive" default / "bulk"); the batcher
  sheds stale and low-priority frames first under pressure, and drops
  anything older than ``shed_stale_after_s`` before it can waste a
  dispatch slot.
- **Brownout controller**: a queue-wait EWMA crossing
  ``BrownoutPolicy.queue_wait_s`` degrades work per frame with hysteresis
  — level 1 skip-k sheds bulk intake, level 2 sheds all bulk and caps the
  dispatch bucket ladder at its smallest rung — announced on the status
  topic with a ``brownout_level`` gauge, recovering automatically.
- **Admission ledger**: every admitted frame ends in exactly one bucket —
  ``admitted == completed + Σ drops_by_reason`` (``ledger()``); shed /
  dead-lettered / abandoned frames also append metadata + reason to the
  optional durable ``DeadLetterJournal`` so producers can retry.

**Durable state** (``runtime.state_store``, wired via ``state_store=``):
an enrolment write-ahead-logs its embeddings/labels (fsynced per policy)
before the gallery mutation and is acknowledged only after — restart
recovery (checkpoint + WAL replay) then loses nothing acknowledged. The
serving loop ticks the lifecycle's checkpoint thresholds each iteration;
the checkpoint itself (host-mirror ``snapshot()`` + atomic checksummed
write) runs on a background thread behind a single-flight guard, so
dispatch never blocks on durability. ``reload_gallery`` forces a
checkpoint — a swap is not WAL-representable.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
from opencv_facerecognizer_tpu.runtime.admission import (
    PRIORITY_INTERACTIVE,
    AdmissionController,
    parse_priority,
)
from opencv_facerecognizer_tpu.runtime.batcher import FrameBatcher
from opencv_facerecognizer_tpu.runtime.connector import (
    MiddlewareConnector,
    decode_frame,
)
from opencv_facerecognizer_tpu.runtime.ingest import (
    JPEG_KEY,
    IngestConfig,
    IngestPipeline,
)
from opencv_facerecognizer_tpu.runtime.resilience import (
    BrownoutPolicy,
    DurabilityDegradedError,
    ResiliencePolicy,
    is_transient_error,
)
from opencv_facerecognizer_tpu.runtime.slo import STATE_CRITICAL
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils import tracing

FRAME_TOPIC = "ocvfacerec/frames"
RESULT_TOPIC = "ocvfacerec/results"
CONTROL_TOPIC = "ocvfacerec/control"
STATUS_TOPIC = "ocvfacerec/status"
#: link-supervision heartbeats (ISSUE 16): the router pings each replica
#: on ``ping``; the service echoes the payload back on ``pong``.  An
#: application-level round trip proves the whole path — connector, wire,
#: dispatch thread — where TCP liveness proves only the kernel's half.
LINK_PING_TOPIC = "ocvfacerec/link/ping"
LINK_PONG_TOPIC = "ocvfacerec/link/pong"

#: Fallback-path readback poll: with ``readback_worker=False`` the inline
#: drain waits for an over-depth/forced head batch by sleeping this long
#: between ``is_ready`` checks (the threaded worker never polls a healthy
#: readback — it blocks on the array). Also the worker's bounded-poll
#: interval for a proxy that refuses to block (injected stuck readback).
FALLBACK_READBACK_POLL_S = 0.005
#: Completion-wait tick: ``drain()``'s condition re-check interval, and the
#: upper bound between liveness re-checks of the worker's condition waits.
#: Only the fallback non-threaded path actually sleeps this blindly.
FALLBACK_DRAIN_POLL_S = 0.05
#: Dispatch bucket ladder (capped at ``batch_size``, filtered to the mesh's
#: dp divisibility): a partial batch is sliced to the smallest bucket >= its
#: real frame count, so light traffic pays small-batch compute without ever
#: compiling a new shape mid-serving.
DEFAULT_BUCKET_SIZES = (8, 32, 128)
#: Default stage-1 cascade operating point (mirrors
#: ``models.cascade.DEFAULT_THRESHOLD`` without importing flax here):
#: frames scoring below it are face-free early exits (``completed_empty``),
#: frames at/above it survive to the full detector.
DEFAULT_CASCADE_THRESHOLD = 0.3
#: How much ``--cascade-threshold`` tightens per brownout escalation: at
#: effective brownout level >= 1 the gate raises its threshold one notch
#: (rejecting MORE borderline frames — shedding device work) BEFORE the
#: intake skip starts dropping admitted bulk frames outright.
CASCADE_BROWNOUT_NOTCH = 0.15


@dataclass
class _Enrolment:
    subject_name: str
    needed: int
    crops: List[np.ndarray] = field(default_factory=list)


class _ReadbackBlocker:
    """One daemon helper thread that performs the potentially-unbounded
    ``block_until_ready`` so the readback worker's wait on a batch can be
    bounded by that batch's deadline. ``block`` returns ``"ready"`` (the
    array's transfer completed), ``"raised"`` (blocking raised — an
    injected never-ready proxy, or a failed computation), or ``"timeout"``
    (deadline passed while still blocked). After a timeout the helper may
    be wedged in native code — the hang-mode outage — so the caller must
    abandon this instance and build a fresh one; the abandoned daemon
    thread parks forever on its own (now unreachable) condition variable.
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._pending: Any = None
        self._done = threading.Event()
        self._ok = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ocvf-readback-blocker")
        self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None:
                    self._cv.wait()
                arr = self._pending
            try:
                arr.block_until_ready()  # ocvf-lint: boundary=host-sync -- THE designed readback wait: this sacrificial blocker thread exists so the worker's wait stays deadline-bounded; the serving loop itself never blocks
                self._ok = True
            except Exception:  # ocvf-lint: disable=swallowed-exception -- failure IS recorded: _ok=False is read by block(), whose caller classifies the outage and dead-letters the batch
                self._ok = False
            with self._cv:
                self._pending = None
            self._done.set()

    def block(self, arr: Any, timeout: float) -> str:
        self._done.clear()
        with self._cv:
            self._pending = arr
            self._cv.notify()
        if not self._done.wait(timeout=max(0.0, timeout)):
            return "timeout"
        return "ready" if self._ok else "raised"


class RecognizerService:
    def __init__(
        self,
        pipeline: RecognitionPipeline,
        connector: MiddlewareConnector,
        batch_size: int = 8,
        frame_shape: Optional[tuple] = None,
        flush_timeout: float = 0.05,
        # Backpressure knob: beyond this many undrained batches the dispatch
        # loop waits for the readback worker to free a slot before popping
        # more. Keep it shallow — each in-flight batch is a full device
        # round-trip of latency debt (~300 ms on a tunneled backend); a deep
        # queue turns into seconds of backlog while the batcher keeps
        # accepting frames.
        inflight_depth: int = 4,
        similarity_threshold: float = 0.3,
        subject_names: Optional[List[str]] = None,
        metrics: Optional[Metrics] = None,
        # uint8 ships frames host->device 4x cheaper (cast to f32 happens
        # in-graph); right whenever the source is 8-bit camera frames.
        transfer_dtype=np.float32,
        # Steady-state failure handling (runtime.resilience docstring).
        resilience: Optional[ResiliencePolicy] = None,
        # Chaos hook (runtime.faults): installs at connector receive,
        # batcher put, device dispatch, and async readback. None in
        # production — every hook site is a no-op without it.
        fault_injector=None,
        # Degraded-mode backend check, injectable for tests. Default runs
        # utils.backend_probe's bounded subprocess probe (allow_cpu=False:
        # "usable" means the accelerator answers, not a CPU fallback).
        backend_probe_fn: Optional[Callable[[], tuple]] = None,
        # Called with this service when degraded mode finds the backend
        # dead: the app wires its CPU re-initialization here (rebuild the
        # pipeline on host devices) so a dead accelerator degrades the
        # job instead of wedging it.
        cpu_fallback: Optional[Callable[["RecognizerService"], None]] = None,
        # False selects the pre-worker inline drain (poll-based) path: the
        # serving loop itself materializes readbacks between dispatches,
        # sleeping on the two named knobs below. Kept as the fallback for
        # backends/hosts where a second Python thread is unwanted, and as
        # the measurable "before" of bench_serving.py's comparison.
        readback_worker: bool = True,
        # Fallback-path poll knobs (module docstring; exposed as
        # ``ocvf-recognize --readback-poll-ms / --drain-poll-ms``).
        readback_poll_s: float = FALLBACK_READBACK_POLL_S,
        drain_poll_s: float = FALLBACK_DRAIN_POLL_S,
        # Dispatch bucket ladder (None/() disables slicing: every batch
        # dispatches at the full padded batch_size, the old behavior).
        bucket_sizes: Optional[Sequence[int]] = DEFAULT_BUCKET_SIZES,
        # Continuous-batching latency target, forwarded to the batcher's
        # adaptive flush deadline (None keeps the fixed flush_timeout).
        target_latency_s: Optional[float] = None,
        # ---- overload protection (module docstring) ----
        # Front-door admission control: rate limits + bounded intake,
        # consulted per frame BEFORE decode. None = admit everything.
        admission: Optional[AdmissionController] = None,
        # Brownout degradation knobs. None disables the controller.
        brownout: Optional[BrownoutPolicy] = None,
        # Durable dead-letter journal (runtime.journal.DeadLetterJournal):
        # shed/dead-lettered/abandoned frames append metadata + reason
        # here. None keeps counter-only accounting.
        dead_letter_journal=None,
        # Freshness bound forwarded to the batcher: queued frames older
        # than this are shed (reason ``stale``) rather than dispatched.
        shed_stale_after_s: Optional[float] = None,
        # Crash-safe state lifecycle (runtime.state_store.StateLifecycle):
        # enrollments write-ahead to its WAL before touching the gallery,
        # the serving loop ticks its checkpoint thresholds, and a reload
        # forces a durable checkpoint. None keeps state memory-only (the
        # pre-durability behavior).
        state_store=None,
        # Frame-lifecycle tracer (utils.tracing.Tracer): per-frame causal
        # spans (receive -> queue_wait -> settle), per-batch spans
        # (dispatch/ready_wait/publish with coalescing ancestry), brownout
        # lifecycle spans, and the flight-recorder dump on dead-letter.
        # None = tracing fully off (zero overhead).
        tracer=None,
        # SLO burn-rate monitor (runtime.slo.SLOMonitor): ticked by the
        # serving loop (evaluation every interval_s); its health verdict
        # feeds /health, the recompile watchdog's warn events, and — at
        # critical — one extra level of brownout intake pressure. None =
        # no SLO evaluation (zero overhead).
        slo_monitor=None,
        # Read-replica role (runtime.replication.ReadReplica): the serving
        # loop polls the shared WAL between batches and applies new
        # enrollment rows through the same gallery.add route replay uses.
        # A service with a replica is read-only for enrollment — enroll
        # commands are rejected with an explicit status (the writer lease
        # in the shared state dir owns the write path). None = this
        # process owns its own state (the pre-replication behavior).
        replica=None,
        # Ingest subsystem config (runtime.ingest.IngestConfig): installs
        # the pre-allocated staging ring in place of the ad-hoc buffer
        # pool, picks the transfer dtype from its mode (overriding
        # ``transfer_dtype``), routes dispatches through the explicit
        # device uploader, and (jpeg mode) runs the off-thread decode
        # worker pool for compressed camera payloads. None = the
        # pre-ingest behavior, unchanged.
        ingest: Optional[IngestConfig] = None,
        # ---- cascade early-exit detection (ISSUE 13) ----
        # Master switch for the two-stage gate (the --no-cascade escape
        # hatch). Active only when the pipeline also carries a stage-1
        # model (``pipeline.cascade`` + ``cascade_scores``); True with a
        # cascade-less pipeline is the unchanged single-stage behavior.
        cascade: bool = True,
        # Stage-1 operating point: frames scoring below it settle as
        # ``completed_empty`` without ever reaching the full detector.
        # None adopts the gate's own trained threshold (or the default).
        cascade_threshold: Optional[float] = None,
        # Brownout integration: threshold tightening per escalation (the
        # cheapest shed — reject borderline frames at stage 1 before the
        # intake skip drops admitted frames outright). 0 disables.
        cascade_brownout_notch: float = CASCADE_BROWNOUT_NOTCH,
        # ---- temporal identity cache (ISSUE 17) ----
        # An IdentityTracker (runtime.tracker) or None. When set, frames
        # whose ``meta["stream"]`` has live confirmed tracks — all inside
        # their re-verify window, appearance-stable and embedder-version
        # matched — settle as ``completed_cached`` with the cached
        # identities BEFORE the cascade gate (a tracker lookup is pure
        # host work, cheaper than the stage-1 device pass); every full
        # published result feeds back through ``tracker.update``. None =
        # every frame takes the full path (the --no-track-cache hatch).
        tracker=None,
        # ---- idempotent intake (ISSUE 16) ----
        # Frame-id dedup window: a delivery whose ``meta["_fid"]`` was
        # already ADMITTED is refused before admission (counted
        # ``frames_deduped``, outside the ledger like rejections), so
        # duplicated transports, retries and hedge re-sends can never
        # double-count the ledger or double-publish a result from this
        # replica. 0 disables; frames without a fid always pass.
        dedup_window: int = 4096,
    ):
        self.pipeline = pipeline
        self.connector = connector
        self.similarity_threshold = float(similarity_threshold)
        self.subject_names = list(subject_names) if subject_names else []
        self.metrics = metrics or Metrics()
        self.resilience = resilience or ResiliencePolicy()
        self._faults = fault_injector
        self._backend_probe_fn = backend_probe_fn
        self._cpu_fallback = cpu_fallback
        self._use_worker = bool(readback_worker)
        self._readback_poll_s = float(readback_poll_s)
        self._drain_poll_s = float(drain_poll_s)
        if frame_shape is None:
            raise ValueError("frame_shape (H, W) is required (static device shapes)")
        self.admission = admission
        if self.admission is not None and self.admission.inflight_fn is None:
            # The bounded intake reads the admission ledger: in-system =
            # admitted - completed - Σ drops (always current, no second
            # bookkeeping to desync).
            self.admission.inflight_fn = self.frames_in_system
        self.brownout_policy = brownout
        self.journal = dead_letter_journal
        self.state = state_store
        self._brownout_level = 0
        self._queue_wait_ewma: Optional[float] = None
        self._brownout_changed_at = 0.0
        self._bulk_seq = 0
        # Aggregated backpressure announcements: one ``rejected`` status
        # per reason per window, carrying the count since the last one —
        # per-frame publishes would amplify the very flood being shed.
        self._reject_note_interval_s = 0.5
        self._reject_pending: Dict[str, int] = {}
        self._reject_last_pub: Dict[str, float] = {}
        self._reject_lock = threading.Lock()
        self.tracer = tracer
        self.slo = slo_monitor
        self.replica = replica
        # Embedder-rollout coordinator (runtime.rollout.RolloutCoordinator),
        # attached by the rollout orchestration when a dual-score parity
        # window is live: _publish samples detected face crops into it
        # (rate-limited, copied, scored on the rollout thread — the hot
        # path pays one attribute read when unset). None = no rollout.
        self.rollout = None
        # Versioned model registry (runtime.registry.ModelRegistry) and
        # the in-flight swap coordinator, attached by the registry
        # orchestration. When ``registry`` is set, published results and
        # the tracker key on the FULL registry stamp (every role), so any
        # role's cutover invalidates cached identity verdicts; when
        # ``registry_swap`` is live, _publish samples whole frames + the
        # serving detector's verdicts into its detection-parity window
        # (same rate-limited, fail-open contract as ``rollout``). Both
        # cost one attribute read on the hot path when unset.
        self.registry = None
        self.registry_swap = None
        # Serving-loop progress stamp, refreshed every loop iteration
        # (batch AND idle — get_batch's flush timeout guarantees regular
        # iterations even with zero traffic). Read by the loop_liveness
        # gauge SLO through ``loop_staleness_s``: empty latency windows
        # read as "no breach", so without this a wedged loop scores a
        # clean /health forever — the gauge is what lets the expo
        # backstop's tick escalate a loop that stopped moving.
        self._loop_progress_t: Optional[float] = None
        # Recompile-watchdog arming flag: only set once warmup() compiled
        # the whole bucket ladder — before that, a jit-cache miss is the
        # expected cost of starting up, not a mid-serving compile.
        self._warmed = False
        # Cascade early-exit gate (ISSUE 13): active iff enabled AND the
        # pipeline carries a stage-1 model. The threshold resolves
        # knob > gate's trained operating point > module default.
        gate = getattr(pipeline, "cascade", None)
        self._cascade_active = (bool(cascade) and gate is not None
                                and hasattr(pipeline, "cascade_scores"))
        if cascade_threshold is None:
            cascade_threshold = getattr(gate, "threshold", None)
        self.cascade_threshold = float(
            DEFAULT_CASCADE_THRESHOLD if cascade_threshold is None
            else cascade_threshold)
        self.cascade_brownout_notch = float(cascade_brownout_notch)
        self.tracker = tracker
        # Cumulative scored/rejected counts behind the /prom rate gauges
        # (serving-thread only — no lock needed).
        self._cascade_scored = 0
        self._cascade_rejected = 0
        self._bucket_ladder = self._build_bucket_ladder(bucket_sizes,
                                                        int(batch_size))
        # Ingest subsystem (runtime.ingest): staging ring sized per
        # dispatch-bucket rung + mode-derived transfer dtype + (jpeg)
        # decode pool. Built BEFORE the batcher, which stages into it.
        self.ingest = None
        if ingest is not None:
            self.ingest = IngestPipeline(
                ingest, self._bucket_ladder, tuple(frame_shape),
                metrics=self.metrics, tracer=tracer,
                trace_topic=FRAME_TOPIC, fault_injector=fault_injector,
                inflight_depth=int(inflight_depth))
            transfer_dtype = self.ingest.transfer_dtype
            if (self.admission is not None
                    and self.admission.staging_free_fn is None):
                # Ring exhaustion backpressures at the front door: a
                # flood that outruns recycle is rejected explicitly
                # (reason ``staging``), never absorbed by an allocation.
                self.admission.staging_free_fn = self.ingest.staging.free_slots
        self.batcher = FrameBatcher(batch_size, frame_shape, flush_timeout,
                                    dtype=transfer_dtype,
                                    metrics=self.metrics,
                                    fault_injector=fault_injector,
                                    target_latency_s=target_latency_s,
                                    stale_after_s=shed_stale_after_s,
                                    drop_log=self._journal_drop,
                                    tracer=tracer,
                                    trace_topic=FRAME_TOPIC,
                                    staging_ring=(self.ingest.staging
                                                  if self.ingest is not None
                                                  else None))
        self.inflight_depth = int(inflight_depth)
        self._inflight: deque = deque()
        # One condition variable guards the in-flight queue AND the
        # completion counter: the dispatch loop appends + waits for slots,
        # the readback worker pops + notifies, drain() waits on it instead
        # of a blind sleep.
        self._inflight_cv = threading.Condition()
        self._blocker: Optional[_ReadbackBlocker] = None
        self._worker: Optional[threading.Thread] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._crashed = False
        self._consecutive_dispatch_failures = 0
        self._degraded = False
        # Completion counter paired with batcher.delivered_batches: a batch
        # counts as completed only once PUBLISHED (or abandoned on dispatch
        # failure / dead-lettered / lost to a crash — every exit settles
        # it), so drain() sees every popped batch through its whole
        # lifetime — there is no window where a batch in hand is invisible
        # (round-2 advisor #3: a bare _dispatching flag had one between
        # get_batch() and the flag write).
        self._completed_batches = 0
        self._enrolment: Optional[_Enrolment] = None
        self._enrol_lock = threading.Lock()
        # Called (no args, best-effort) after every COMMITTED gallery
        # change — a finished enrolment, a reload_gallery swap. This is a
        # direct callback, not a status-topic subscription, deliberately:
        # wire connectors (JSONL/socket) publish outbound only and never
        # dispatch their own publishes to local subscribers, so a
        # supervisor listening on STATUS_TOPIC would never hear commits in
        # production. ServiceSupervisor registers its checkpoint here.
        self.commit_hooks: List[Callable[[], None]] = []
        if self.state is not None:
            # The lifecycle reads the LIVE pipeline's gallery at
            # checkpoint time (reload/CPU-fallback may swap it) and nudges
            # its thresholds through the commit hooks just registered.
            self.state.attach(self)
            # Degraded-durability announcements (ISSUE 15) ride the same
            # status channel as the dispatch-side degraded mode: wire the
            # monitor's publish hook unless the app already did.
            dur = getattr(self.state, "durability", None)
            if dur is not None and dur.publish is None:
                dur.publish = self._publish_status

        # Enrolment embeds ride a FIXED-size padded chunk: one compiled
        # shape, warmed at start(), so an enroll command never triggers a
        # mid-serving XLA compile (measured ~85 s stall on this backend).
        self._enrol_chunk = 8

        def _embed_chunk(params, crops):
            from opencv_facerecognizer_tpu.models.embedder import normalize_faces

            return self.pipeline.embed_net.apply(
                {"params": params},
                normalize_faces(crops, self.pipeline.face_size),
            )

        import jax

        self._embed_chunk = jax.jit(_embed_chunk)  # ocvf-lint: boundary=jit-recompile-hazard -- built once at construction for ONE fixed chunk shape; warmup() compiles it before serving starts
        # Placement override for the enrolment graph. None = default
        # backend. rebuild_pipeline_on_cpu pins this to the CPU device it
        # rebuilt on: the bare jit above takes uncommitted numpy inputs
        # and would otherwise keep dispatching enrolment embeds on the
        # dead accelerator after a CPU fallback.
        self._embed_device = None

        # Idempotent-intake window (ISSUE 16): fids of ADMITTED frames,
        # set for O(1) membership + deque for FIFO eviction. Sized so a
        # legitimately re-sent frame (hedge, retry after a partition
        # heals) is still remembered long after its twin completed —
        # the window bounds memory, not correctness, because a fid that
        # was evicted AND re-delivered that late would need > window
        # admissions in between.
        self._dedup_window = max(0, int(dedup_window))
        self._dedup_seen: set = set()
        self._dedup_order: deque = deque()
        self._dedup_lock = threading.Lock()

        connector.subscribe(FRAME_TOPIC, self._on_frame)
        connector.subscribe(CONTROL_TOPIC, self._on_control)
        connector.subscribe(LINK_PING_TOPIC, self._on_link_ping)

    def _build_bucket_ladder(self, bucket_sizes, batch_size: int) -> List[int]:
        """Ascending dispatch sizes, always ending at ``batch_size``. Only
        ladder entries the mesh can shard (divisible by every dp axis the
        pipeline dispatches over) survive the filter."""
        divisor = 1
        try:
            from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS

            for mesh in (getattr(getattr(self.pipeline, "gallery", None),
                                 "mesh", None),
                         getattr(self.pipeline, "mesh_a", None)):
                if mesh is not None:
                    divisor = max(divisor, int(mesh.shape[DP_AXIS]))
        except Exception:  # ocvf-lint: disable=swallowed-exception -- config probe at construction: stub/fake pipelines legitimately have no mesh, divisor=1 is the documented fallback
            divisor = 1
        ladder = {int(b) for b in (bucket_sizes or ())
                  if 0 < int(b) < batch_size and int(b) % divisor == 0}
        ladder.add(batch_size)
        return sorted(ladder)

    def _pick_bucket(self, count: int) -> int:
        for b in self._bucket_ladder:
            if count <= b:
                return b
        return self.batcher.batch_size

    # ---- admission ledger (overload layer §4) ----

    #: every way an ADMITTED frame can leave the system other than being
    #: published: the ledger invariant is
    #: ``frames_admitted == frames_completed + Σ(these)`` once the system
    #: is quiescent (``in_system`` = the live remainder otherwise).
    #: Pre-admission rejections (``frames_rejected_*``) are outside by
    #: design — a rejected frame never entered.
    LEDGER_DROP_COUNTERS = mn.LEDGER_DROP_COUNTERS

    def ledger(self) -> Dict[str, Any]:
        """One atomic admission-ledger snapshot: ``admitted``,
        ``completed``, ``completed_empty`` (cascade early exits — frames
        published with an empty face list because stage 1 scored them
        face-free; terminal completions, not drops), ``completed_cached``
        (track-cache exits, ISSUE 17: published with the cached
        identities, never dispatched — terminal completions too),
        per-reason ``drops_by_reason`` and the ``in_system`` remainder
        (frames admitted but not yet finished — queued in the batcher,
        riding an in-flight batch, or mid-publish). The invariant is
        ``admitted == completed + completed_empty + completed_cached +
        Σ drops`` at quiescence (after ``drain()``, ``in_system`` must be
        exactly 0) — chaos_soak and the overload/cascade/tracker tests
        enforce it."""
        c = self.metrics.counters()
        drops = {name: c[name] for name in self.LEDGER_DROP_COUNTERS
                 if c.get(name)}
        admitted = c.get(mn.FRAMES_ADMITTED, 0.0)
        completed = c.get(mn.FRAMES_COMPLETED, 0.0)
        completed_empty = c.get(mn.FRAMES_COMPLETED_EMPTY, 0.0)
        completed_cached = c.get(mn.FRAMES_COMPLETED_CACHED, 0.0)
        return {
            "admitted": admitted,
            "completed": completed,
            "completed_empty": completed_empty,
            "completed_cached": completed_cached,
            "drops_by_reason": drops,
            "in_system": (admitted - completed - completed_empty
                          - completed_cached - sum(drops.values())),
        }

    def frames_in_system(self) -> float:
        """Admitted-but-unfinished frame count (the admission bound's
        signal). One atomic allocation-free counter read (this runs per
        offered frame on the connector thread, under exactly the flood it
        exists to shed); it can transiently lag a frame mid-transition
        between buckets — fine for a bound, exactness is only claimed at
        quiescence."""
        return max(0.0, self.metrics.sum_counters(
            (mn.FRAMES_ADMITTED,),
            (mn.FRAMES_COMPLETED, mn.FRAMES_COMPLETED_EMPTY,
             mn.FRAMES_COMPLETED_CACHED)
            + self.LEDGER_DROP_COUNTERS))

    def _journal_drop(self, reason: str, entries: List[Dict[str, Any]],
                      **extra) -> None:
        """Append shed/lost frames to the dead-letter journal (no-op
        without one). Also the batcher's ``drop_log`` hook. Entries carry
        ``trace_id`` + the ``stage`` the frame died at, so a replay can
        reconstruct exactly where each dropped frame's lifecycle ended."""
        if self.journal is not None:
            self.journal.append(reason, entries, **extra)

    @staticmethod
    def _drop_entries(metas, enqueue_ts, trace_ids, stage: str,
                      priority=None) -> List[Dict[str, Any]]:
        """Journal entries for a run of dropped frames, aligned by index
        (missing provenance lists degrade to None fields, same as the
        pre-tracing rows)."""
        n = len(metas)
        return [{
            "meta": metas[i],
            "enqueue_ts": (enqueue_ts[i] if enqueue_ts is not None
                           and i < len(enqueue_ts) else None),
            "priority": priority,
            "trace_id": (trace_ids[i] or None) if trace_ids is not None
                        and i < len(trace_ids) else None,
            "stage": stage,
        } for i in range(n)]

    def _trace_settle(self, trace_ids, outcome: str, where: str,
                      batch: int = 0) -> None:
        """Terminal ``settle`` span for each traced frame in the run —
        every admitted frame must emit exactly one, with ``outcome``
        either ``completed`` or the ledger drop counter it landed in (the
        span-level mirror of the admission-ledger invariant)."""
        tracer = self.tracer
        if tracer is None:
            return
        for tid in trace_ids or ():
            if tid:
                tracer.emit(tid, tracing.SETTLE_STAGE, topic=FRAME_TOPIC,
                            outcome=outcome, where=where, batch=batch)

    def _note_rejection(self, reason: str) -> None:
        """Count + (rate-limited) announce one admission rejection. The
        status message aggregates everything since the last announcement
        for that reason — a backpressure signal, not a per-frame echo, so
        it carries no per-frame fields (an aggregated window mixes
        priorities; stamping one would mislead a consumer throttling a
        specific producer class)."""
        self.metrics.incr(mn.FRAMES_REJECTED_PREFIX + reason)
        now = time.monotonic()
        with self._reject_lock:
            self._reject_pending[reason] = self._reject_pending.get(reason, 0) + 1
            last = self._reject_last_pub.get(reason, 0.0)
            if now - last < self._reject_note_interval_s:
                return
            count = self._reject_pending.pop(reason)
            self._reject_last_pub[reason] = now
        self._publish_status({"status": "rejected", "reason": reason,
                              "count": count})

    def _flush_rejections(self, force: bool = False) -> None:
        """Trailing-edge flush of aggregated rejections: when a flood
        stops mid-window, the counts still pending would otherwise never
        be announced (only a LATER rejection of the same reason triggers a
        publish). Called from the serving loop's idle tick; stop() forces
        a final flush regardless of the window."""
        now = time.monotonic()
        flush = []
        with self._reject_lock:
            for reason in list(self._reject_pending):
                if force or (now - self._reject_last_pub.get(reason, 0.0)
                             >= self._reject_note_interval_s):
                    flush.append((reason, self._reject_pending.pop(reason)))
                    self._reject_last_pub[reason] = now
        for reason, count in flush:
            self._publish_status({"status": "rejected", "reason": reason,
                                  "count": count})

    # ---- brownout controller (overload layer §2) ----

    @property
    def brownout_level(self) -> int:
        return self._brownout_level

    def _note_queue_wait(self, seconds: float) -> None:
        """Feed the brownout controller's queue-wait EWMA (called per
        batch with the batch's mean queue wait, and with 0.0 on idle ticks
        so an emptied queue recovers even when traffic stops entirely)."""
        if self.brownout_policy is None:
            return
        policy = self.brownout_policy
        prev = self._queue_wait_ewma
        self._queue_wait_ewma = (seconds if prev is None
                                 else prev + policy.ewma_alpha * (seconds - prev))
        self._update_brownout()

    def _update_brownout(self) -> None:
        policy = self.brownout_policy
        now = time.monotonic()
        if now - self._brownout_changed_at < policy.dwell_s:
            return  # hysteresis dwell: no flapping between batches
        ewma = self._queue_wait_ewma or 0.0
        level = self._brownout_level
        if ewma > policy.queue_wait_s and level < policy.max_level:
            self._set_brownout(level + 1, ewma)
        elif ewma < policy.exit_ratio * policy.queue_wait_s and level > 0:
            self._set_brownout(level - 1, ewma)

    def _set_brownout(self, level: int, ewma: float) -> None:
        prev = self._brownout_level
        self._brownout_level = level
        self._brownout_changed_at = time.monotonic()
        self.metrics.set_gauge(mn.BROWNOUT_LEVEL, level)
        if self.tracer is not None:
            # Instant lifecycle span: level transitions are the overload
            # story's causal markers (a queue-wait balloon followed by a
            # brownout span explains the shed settle spans after it).
            self.tracer.emit(self.tracer.new_trace(), "brownout",
                             topic=tracing.LIFECYCLE_TOPIC, level=level,
                             from_level=prev,
                             queue_wait_ewma_ms=round(ewma * 1e3, 2))
        if level > 0:
            self.metrics.incr(mn.BROWNOUT_TRANSITIONS)
            self._publish_status({"status": "brownout", "level": level,
                                  "queue_wait_ewma_ms": round(ewma * 1e3, 2)})
        else:
            self.metrics.incr(mn.BROWNOUT_RECOVERIES)
            self._publish_status({"status": "brownout_recovered",
                                  "queue_wait_ewma_ms": round(ewma * 1e3, 2)})

    def _effective_brownout_level(self) -> int:
        """The controller's level, plus one when the SLO monitor reads
        critical — the health verdict as a brownout INPUT: a blown error
        budget sheds bulk intake even before the queue-wait EWMA catches
        up, and stops the moment health de-escalates. Only the intake
        skip consumes the boost; the controller's own level/hysteresis
        (and its recovery) are untouched, so SLO pressure can never wedge
        the brownout state machine."""
        level = self._brownout_level
        if (self.slo is not None and self.brownout_policy is not None
                and self.slo.state_code >= STATE_CRITICAL):
            level = min(self.brownout_policy.max_level, level + 1)
        return level

    def _brownout_sheds_intake(self, priority: int, level: int) -> bool:
        """Shed this (already admitted) frame at intake? Interactive
        frames never (the intake skip is the priority-aware half of
        brownout; the level-2 ladder trim in ``_serve_one`` is the
        class-blind half — see BrownoutPolicy's docstring); bulk frames
        skip-k at level 1, always at ``max_level``. ``level`` is the
        caller's one ``_effective_brownout_level()`` read (incl. the SLO
        critical-health boost) — the same read is journaled with the
        drop, so the recorded level is the one that caused it."""
        if level <= 0 or priority <= PRIORITY_INTERACTIVE:
            return False
        if level >= self.brownout_policy.max_level:
            return True
        self._bulk_seq += 1
        return self._bulk_seq % max(2, self.brownout_policy.bulk_skip) != 0

    def _brownout_bucket_cap(self) -> Optional[int]:
        """At max brownout level the dispatch ladder is capped at its
        smallest rung (one small fast device call per batch); else None."""
        if (self.brownout_policy is not None
                and self._brownout_level >= self.brownout_policy.max_level):
            return self._bucket_ladder[0]
        return None

    # ---- cascade early-exit gate (ISSUE 13) ----

    def _effective_cascade_threshold(self) -> float:
        """The stage-1 operating threshold, tightened one notch while
        brownout pressure is on (effective level >= 1, incl. the SLO
        critical boost): rejecting borderline frames at stage 1 is the
        cheapest possible shed — it saves whole stage-2 dispatches
        BEFORE the intake skip starts dropping admitted frames
        outright. The gauge on /prom always shows the EFFECTIVE value."""
        thr = self.cascade_threshold
        if (self.brownout_policy is not None and self.cascade_brownout_notch
                and self._effective_brownout_level() >= 1):
            thr = min(0.99, thr + self.cascade_brownout_notch)
        return thr

    def _cascade_keep_mask(self, frames, count: int,
                           batch_tid: int) -> Optional[np.ndarray]:
        """One stage-1 pass over the batch's dispatch rung: returns the
        per-frame keep mask (True = face-possible, survives to the full
        detector) for the first ``count`` frames, or None when stage 1
        is unavailable this batch — a scoring error fails OPEN to the
        full chain (the cascade may save device time, never cost
        availability). The tiny [B]-float readback here IS the
        early-exit decision point; its host wall (incl. that readback)
        lands in the ``cascade_score`` window."""
        thr = self._effective_cascade_threshold()
        t0 = time.perf_counter()
        bucket = self._pick_bucket(count)
        view = frames[:bucket] if bucket < len(frames) else frames
        try:
            scores = np.asarray(self.pipeline.cascade_scores(view))  # ocvf-lint: boundary=host-sync -- the cascade's designed decision readback: a [B]-float materialize whose entire purpose is deciding whether the expensive stage-2 dispatch happens at all (ISSUE 13)
        except Exception:  # noqa: BLE001 — fail open: stage 2 serves the batch
            logging.getLogger(__name__).exception(
                "cascade stage-1 scoring failed; serving the full batch")
            self.metrics.incr(mn.CASCADE_ERRORS)
            return None
        dur = time.perf_counter() - t0
        self.metrics.observe(mn.CASCADE_SCORE, dur)
        info = getattr(self.pipeline, "last_cascade_info", None) or {}
        if self._warmed and info.get("cache_hit") is False:
            self._note_recompile(bucket, count, "cascade")
        keep = np.asarray(scores)[:count] >= thr
        if self._faults is not None:
            # Chaos boundary: ``cascade: reject_all`` forces the
            # pathological all-face-free verdict (runtime.faults).
            keep = self._faults.on_cascade(keep)
        rejected = count - int(keep.sum())
        self._cascade_scored += count
        self._cascade_rejected += rejected
        self.metrics.incr(mn.CASCADE_FRAMES_SCORED, count)
        reject_rate = self._cascade_rejected / max(1, self._cascade_scored)
        self.metrics.set_gauge(mn.CASCADE_REJECT_RATE, reject_rate)
        self.metrics.set_gauge(mn.CASCADE_PASS_RATE, 1.0 - reject_rate)
        self.metrics.set_gauge(mn.CASCADE_THRESHOLD, thr)
        if batch_tid:
            self.tracer.emit(batch_tid, "cascade", topic=tracing.BATCH_TOPIC,
                             dur=dur, frames=count, rejected=rejected,
                             threshold=round(thr, 4))
        return keep

    def _complete_empty(self, rejected, batch_tid: int) -> None:
        """Settle cascade-rejected frames as ``completed_empty``: each
        publishes a result with an empty face list (producers get an
        answer for every admitted frame — the uplift bench counts
        completions through the same result stream) and lands in the
        ledger's ``completed_empty`` bucket with a terminal settle span.
        ``rejected`` rows are ``(meta, enqueue_ts, trace_id, priority)``.
        A crash escaping mid-run settles the remainder as crashed,
        exactly like ``_publish`` — no frame is ever left in limbo."""
        if self.tracker is not None:
            # A face-free verdict on a tracked stream is a miss for its
            # live tracks: a vanished subject ages out within the miss
            # TTL instead of being served from a stale cache entry.
            for meta, _ts, _tid, _pri in rejected:
                key = self._track_stream_key(meta)
                if key is not None:
                    try:
                        self.tracker.note_miss(key)
                    except Exception:  # noqa: BLE001 — observation only
                        self.metrics.incr(mn.TRACK_ERRORS)
        published = 0
        try:
            for meta, _ts, _tid, _pri in rejected:
                self.connector.publish(RESULT_TOPIC,
                                       {"meta": meta, "faces": [],
                                        "exit": "cascade"})
                published += 1
        finally:
            self.metrics.incr(mn.FRAMES_COMPLETED_EMPTY, published)
            self._trace_settle([r[2] for r in rejected[:published]],
                               tracing.OUTCOME_COMPLETED_EMPTY,
                               "cascade.reject", batch=batch_tid)
            if published < len(rejected):
                self.metrics.incr(mn.FRAMES_DROPPED_CRASHED,
                                  len(rejected) - published)
                self._trace_settle([r[2] for r in rejected[published:]],
                                   mn.FRAMES_DROPPED_CRASHED,
                                   "cascade.publish_crashed",
                                   batch=batch_tid)
            # Early exits are real end-to-end completions: their latency
            # belongs in the SLO histograms like any published frame.
            now_mono = time.monotonic()
            for _meta, ts, _tid, pri in rejected[:published]:
                if ts is not None:
                    self._observe_e2e(ts, pri, now_mono)

    # ---- temporal identity cache (ISSUE 17) ----

    @staticmethod
    def _track_stream_key(meta):
        """The tracking scope of one frame: its camera stream/topic from
        ``meta`` (``stream`` preferred, ``topic`` accepted — the same key
        PR 10's rendezvous routing pins to one replica). None = the frame
        is untracked (no cache lookup, no track update) — frames without
        a stream identity can never alias each other's tracks."""
        if isinstance(meta, dict):
            key = meta.get("stream")
            if key is None:
                key = meta.get("topic")
            return key
        return None

    def _track_reverify_stretch(self) -> float:
        """Brownout composition (mirrors the cascade threshold notch): at
        effective level >= 1 the re-verify interval stretches by the
        tracker's configured factor — serving MORE frames from the cache
        (bounded staleness) is a cheaper shed than dropping admitted
        intake outright."""
        if (self.tracker is not None and self.brownout_policy is not None
                and self._effective_brownout_level() >= 1):
            return float(self.tracker.config.brownout_stretch)
        return 1.0

    def _model_stamp(self, gallery_ver):
        """The tracker/publish model stamp: the plain embedder version
        when no registry is wired (PR 17 behavior, unchanged), else the
        FULL registry stamp as a sorted (role, version) tuple with the
        embedder slot overridden by the dispatch-time gallery version.
        The tracker compares stamps by opaque equality, so keying on the
        tuple makes ANY role's cutover invalidate cached identity
        verdicts — a new detector changes which faces exist, not just
        their embeddings."""
        reg = self.registry
        if reg is None:
            return gallery_ver
        stamp = reg.stamp()
        if gallery_ver is not None:
            stamp["embedder"] = int(gallery_ver)
        return tuple(sorted(stamp.items()))

    @staticmethod
    def _stamp_fields(stamp):
        """Split a model stamp into its published fields: the plain int
        ``embedder_version`` and, when the stamp is a full registry
        tuple, the role->version dict for ``payload["registry"]``."""
        if isinstance(stamp, tuple):
            roles = {str(k): int(v) for k, v in stamp}  # ocvf-lint: boundary=host-sync -- stamps are plain Python ints (registry manifest versions + the gallery's host-side version counter); nothing device-resident ever enters a stamp tuple
            emb = roles.get("embedder")
            return emb, roles
        return stamp, None

    def flush_model_caches(self, stamp=None, reason: str = "registry"
                           ) -> int:
        """Eager identity-cache invalidation on a registry cutover (the
        swap coordinator's ``flush_fn``): every cached tracker verdict
        was produced by the pre-swap model set, so flush now instead of
        waiting for each track's lazy stamp-mismatch eviction. The
        cascade's per-frame verdicts live in the same served results, so
        the tracker flush covers both PR 17 and PR 13 caches; the jit
        COMPILE caches are untouched — params are call arguments, a
        same-architecture swap never recompiles. Returns tracks
        flushed."""
        del stamp  # the flush is total; the stamp is provenance only
        flushed = 0
        if self.tracker is not None:
            try:
                flushed = self.tracker.flush_all(reason=reason)
            except Exception:  # noqa: BLE001 — cache only, fail open
                logging.getLogger(__name__).exception(
                    "tracker flush on registry cutover failed")
                self.metrics.incr(mn.TRACK_ERRORS)
        self.metrics.incr(mn.REGISTRY_CACHE_FLUSHES)
        return flushed

    def _track_lookup(self, meta, frame, gallery_ver, stretch: float):
        """One fail-open cache consult: the cached payload or None. A
        tracker bug must cost the cache win, never the frame — the full
        pipeline is always the safe answer."""
        key = self._track_stream_key(meta)
        if key is None:
            return None
        try:
            return self.tracker.lookup(key, frame,
                                       embedder_version=gallery_ver,
                                       reverify_stretch=stretch)
        except Exception:  # noqa: BLE001 — fail open to the full path
            logging.getLogger(__name__).exception("tracker lookup failed")
            self.metrics.incr(mn.TRACK_ERRORS)
            return None

    def _complete_cached(self, cached, batch_tid: int) -> None:
        """Settle track-cache hits as ``completed_cached``: each
        publishes the cached identities (``exit: track_cache`` plus the
        serving ``track_id``) and lands in the ledger's
        ``completed_cached`` bucket with a terminal settle span — the
        ``_complete_empty`` pattern (ISSUE 13) for the cache exit.
        ``cached`` rows are ``(meta, enqueue_ts, trace_id, priority,
        hit)`` where ``hit`` is the tracker's lookup payload. A crash
        escaping mid-run settles the remainder as crashed."""
        published = 0
        try:
            for meta, _ts, _tid, _pri, hit in cached:
                payload = {"meta": meta, "faces": hit["faces"],
                           "exit": "track_cache",
                           "track_id": hit["track_id"]}
                emb_ver, reg_roles = self._stamp_fields(
                    hit.get("embedder_version"))
                if emb_ver is not None:
                    payload["embedder_version"] = emb_ver
                if reg_roles is not None:
                    payload["registry"] = reg_roles
                self.connector.publish(RESULT_TOPIC, payload)
                published += 1
                self.metrics.incr(mn.FACES_FOUND, len(hit["faces"]))
        finally:
            self.metrics.incr(mn.FRAMES_COMPLETED_CACHED, published)
            self._trace_settle([r[2] for r in cached[:published]],
                               tracing.OUTCOME_COMPLETED_CACHED,
                               "track_cache.hit", batch=batch_tid)
            if published < len(cached):
                self.metrics.incr(mn.FRAMES_DROPPED_CRASHED,
                                  len(cached) - published)
                self._trace_settle([r[2] for r in cached[published:]],
                                   mn.FRAMES_DROPPED_CRASHED,
                                   "track_cache.publish_crashed",
                                   batch=batch_tid)
            # Cache exits are real end-to-end completions: their latency
            # belongs in the SLO histograms like any published frame.
            now_mono = time.monotonic()
            for _meta, ts, _tid, pri, _hit in cached[:published]:
                if ts is not None:
                    self._observe_e2e(ts, pri, now_mono)

    def _observe_e2e(self, enqueue_ts: float, priority: int,
                     now_mono: float) -> None:
        """One frame's end-to-end latency (batcher enqueue -> result
        publish) into the SLO histograms, split by priority class —
        shared by the publish path and the cascade's empty completions so
        the interactive objective sees every answered frame once."""
        e2e = now_mono - enqueue_ts
        self.metrics.observe(mn.E2E_LATENCY, e2e)
        if priority <= PRIORITY_INTERACTIVE:
            self.metrics.observe(mn.E2E_LATENCY_INTERACTIVE, e2e)

    def _note_recompile(self, bucket: int, frames_n: int, mode) -> None:
        """Recompile watchdog: a serving-path jit-cache miss AFTER
        warmup compiled the whole ladder (both cascade stages included)
        is a mid-serving XLA compile the prewarm design exists to
        prevent (measured ~85 s stalls on the tunneled backend).
        Counted, spanned, and reported as a warn-level SLO event so
        /health shows it within one evaluation interval."""
        self.metrics.incr(mn.RECOMPILES_POST_WARMUP)
        if self.tracer is not None:
            self.tracer.emit(self.tracer.new_trace(), "recompile",
                             topic=tracing.LIFECYCLE_TOPIC, bucket=bucket,
                             frames=frames_n, mode=mode)
        if self.slo is not None:
            self.slo.note_event("recompile_post_warmup")

    def _run_embed_chunk(self, params, crops):
        """One fixed-size enrolment embed, honoring ``_embed_device``
        (``jax.default_device`` participates in the jit cache key, so the
        retargeted call compiles for — and runs on — the pinned device)."""
        import contextlib

        import jax

        ctx = (jax.default_device(self._embed_device)
               if self._embed_device is not None else contextlib.nullcontext())
        with ctx:
            return self._embed_chunk(params, crops)

    # ---- connector handlers (dispatch thread; keep cheap) ----

    def _on_frame(self, topic: str, message: Dict[str, Any]) -> None:
        # Connector-receive fault boundary: the injector may drop,
        # duplicate, flood, or corrupt the delivery (runtime.faults).
        messages = ([message] if self._faults is None
                    else self._faults.on_receive(message))
        tracer = self.tracer
        for msg in messages:
            priority = parse_priority(msg.get("priority"))
            # Trace starts at receive: the span covers wire-decode (when
            # the connector stamped ``_recv_ts``) through the admission
            # verdict. tid 0 = sampled out; every emit below no-ops.
            tid = tracer.start_trace(topic) if tracer is not None else 0
            if tid:
                # ``_recv_ts`` is an optional producer/transport stamp
                # (monotonic) for wire transports that record parse time;
                # absent it, the receive span starts at handler entry.
                t_recv = msg.get("_recv_ts") or time.monotonic()
            # Idempotent intake (ISSUE 16): a fid this replica already
            # ADMITTED is refused before admission — like rejections,
            # dedup sits OUTSIDE the ledger, so a duplicated transport
            # or hedge re-send can never double-count it. Checked before
            # admit, recorded only AFTER admit succeeds: a frame whose
            # first delivery was rejected stays re-admittable on retry.
            meta = msg.get("meta")  # caller passthrough — ANY type
            fid = (meta.get("_fid")
                   if self._dedup_window and isinstance(meta, dict)
                   else None)
            if fid is not None and self._dedup_hit(fid):
                self.metrics.incr(mn.FRAMES_DEDUPED)
                if tid:
                    tracer.emit(tid, "receive", topic=topic, t0=t_recv,
                                dur=time.monotonic() - t_recv,
                                verdict="deduped", priority=priority)
                continue
            # Admission FIRST, decode second: a rejected frame must cost
            # ~nothing (the whole point of shedding at the front door).
            if self.admission is not None:
                reason = self.admission.admit(topic, priority)
                if reason is not None:
                    self._note_rejection(reason)
                    if tid:
                        # Rejected pre-admission: outside the ledger by
                        # design — the receive span IS the terminal one.
                        tracer.emit(tid, "receive", topic=topic, t0=t_recv,
                                    dur=time.monotonic() - t_recv,
                                    verdict="rejected_" + reason,
                                    priority=priority)
                    continue
            # Admitted: from here on the frame is the ledger's problem —
            # it must end as completed or as exactly one counted drop.
            if fid is not None:
                self._dedup_record(fid)
            self.metrics.incr(mn.FRAMES_ADMITTED)
            if tid:
                tracer.emit(tid, "receive", topic=topic, t0=t_recv,
                            dur=time.monotonic() - t_recv,
                            verdict="admitted", priority=priority)
            if JPEG_KEY in msg and (self.ingest is not None
                                    and self.ingest.decoder is not None):
                # Compressed intake: hand the ADMITTED payload to the
                # decode pool — the connector thread never decodes. A
                # full decode queue is an explicit ledger drop (the
                # bounded-backlog mirror of the batcher's overflow).
                if not self.ingest.submit_decode(msg, priority, tid):
                    self.metrics.incr(mn.FRAMES_DROPPED_DECODE)
                    self._trace_settle([tid], mn.FRAMES_DROPPED_DECODE,
                                       "ingest.decode_backlog")
                    self._journal_drop("decode_backlog", self._drop_entries(
                        [msg.get("meta")], None, [tid],
                        "ingest.decode_backlog", priority=priority))
                continue
            # A JPEG payload with no decode pool falls through: the pixel
            # decode below fails and the frame counts malformed — the
            # operator forgot --ingest-mode jpeg, loudly.
            try:
                frame = decode_frame(msg) if "__frame__" in msg else np.asarray(
                    msg["frame"]
                )
            except Exception:
                self.metrics.incr(mn.FRAMES_MALFORMED)
                self._trace_settle([tid], mn.FRAMES_MALFORMED, "decode")
                continue
            self._intake_frame(frame, msg.get("meta"), priority, tid)

    def _dedup_hit(self, fid) -> bool:
        """True iff ``fid`` was already admitted within the window."""
        with self._dedup_lock:
            return fid in self._dedup_seen

    def _dedup_record(self, fid) -> None:
        """Remember an admitted fid; FIFO-evict past the window bound."""
        with self._dedup_lock:
            if fid in self._dedup_seen:
                return
            self._dedup_seen.add(fid)
            self._dedup_order.append(fid)
            while len(self._dedup_order) > self._dedup_window:
                self._dedup_seen.discard(self._dedup_order.popleft())

    def _on_link_ping(self, topic: str, message: Dict) -> None:
        """Link-supervision echo (ISSUE 16): bounce the router's ping
        payload back on the pong topic. Runs on the connector dispatch
        thread — proving exactly the path frames travel — and stays
        O(1): a replica too wedged to echo is, for routing purposes,
        down, which is the honest answer."""
        try:
            pong = dict(message) if isinstance(message, dict) else {}
            pong["replica"] = self.replica or pong.get("replica")
            self.connector.publish(LINK_PONG_TOPIC, pong)
        except Exception:  # ocvf-lint: disable=swallowed-exception -- a failed echo IS the signal: the router's pong deadline turns silence into a link-down verdict
            pass

    def _intake_frame(self, frame, meta, priority: int, tid: int) -> None:
        """Post-decode intake shared by the connector handler and the
        decode workers: brownout shed, then the batcher put. Runs on the
        connector's dispatch thread or a decode worker — keep cheap."""
        brownout_level = self._effective_brownout_level()
        if self._brownout_sheds_intake(priority, brownout_level):
            self.metrics.incr(mn.FRAMES_DROPPED_BROWNOUT)
            self._trace_settle([tid], mn.FRAMES_DROPPED_BROWNOUT,
                               "intake.brownout")
            # Journal the EFFECTIVE level (incl. the SLO critical
            # boost) — it is what caused this drop; the raw controller
            # level alone could read 0 here, hiding the cause.
            self._journal_drop("brownout", self._drop_entries(
                [meta], None, [tid], "intake.brownout",
                priority=priority),
                level=brownout_level)
            return
        if not self.batcher.put(frame, meta=meta,
                                priority=priority, trace_id=tid):
            self.metrics.incr(mn.FRAMES_DROPPED)

    def _intake_decoded(self, frame, message, priority: int,
                        tid: int) -> None:
        """Decode-pool success sink: the decoded pixel frame joins the
        normal intake (shape validation in the batcher still guards it —
        a camera sending the wrong resolution drops malformed, counted).
        Contains its own failures: the intake path's settlement effects
        (journal append, span emit) are non-raising by contract, so an
        exception here almost surely PRECEDED settlement — settling the
        frame as a decode drop is the right bias, and doing it HERE
        (where the ledger semantics live) keeps the pool's backstop from
        ever having to guess."""
        try:
            self._intake_frame(frame, message.get("meta"), priority, tid)
        except Exception:  # noqa: BLE001 — an intake bug costs this frame's result, never a decode worker; the ledger settles it below
            logging.getLogger(__name__).exception(
                "decoded-frame intake failed; settling as decode drop")
            self._decode_failed(message, priority, tid, "decode_error")

    def _decode_failed(self, message, priority: int, tid: int,
                       reason: str) -> None:
        """Decode-pool failure sink: a corrupt/truncated compressed
        payload dead-letters with exact ledger settlement — one counted
        drop, one journal row, one terminal span."""
        self.metrics.incr(mn.FRAMES_DROPPED_DECODE)
        self._trace_settle([tid], mn.FRAMES_DROPPED_DECODE, "ingest.decode")
        self._journal_drop(reason, self._drop_entries(
            [message.get("meta")], None, [tid], "ingest.decode",
            priority=priority))

    def _on_control(self, topic: str, message: Dict[str, Any]) -> None:
        cmd = message.get("cmd")
        if cmd == "enroll" and self.replica is not None:
            # Read replicas fail enrollment closed: the writer lease owns
            # the WAL, and a reader mutating its local gallery outside the
            # replication stream would permanently fork it from the
            # writer's history.
            self.metrics.incr(mn.REPLICATION_ENROLL_REJECTED)
            self._publish_status({"status": "rejected",
                                  "reason": "read_replica",
                                  "detail": "enrollment is writer-only; "
                                            "route enroll to the writer "
                                            "replica"})
            return
        if cmd == "enroll":
            dur = getattr(self.state, "durability", None)
            if dur is not None and dur.degraded:
                # Refused CLOSED at the front door (ISSUE 15): while
                # durability is degraded an accepted enroll command would
                # collect crops only to fail its WAL append — the ack
                # never lies, so the refusal is explicit and immediate.
                self.metrics.incr(mn.ENROLLMENTS_REFUSED_DEGRADED)
                self._publish_status({
                    "status": "rejected",
                    "reason": "durability_degraded",
                    "detail": "enrollment refused: WAL durability is "
                              "degraded on this writer (serving "
                              "continues; re-arms automatically when "
                              "the probe sees the disk recover)"})
                return
            name = str(message.get("subject", f"subject_{len(self.subject_names)}"))
            count = int(message.get("count", 5))
            with self._enrol_lock:
                # The label is assigned (and subject_names grown) only when
                # _finish_enrolment succeeds — an abandoned or superseded
                # enrolment must not leave a name with zero gallery rows.
                self._enrolment = _Enrolment(name, count)
            self.connector.publish(STATUS_TOPIC, {"status": "enrolling", "subject": name,
                                                  "count": count})
        elif cmd == "stats":
            status = {"status": "stats",
                      **self.metrics.summary(),
                      **self.batcher.stats,
                      "degraded": self._degraded,
                      "brownout_level": self._brownout_level,
                      "ledger": self.ledger(),
                      "gallery_size": self.pipeline.gallery.size}
            if self.ingest is not None:
                status["ingest"] = self.ingest.stats()
            if self._cascade_active:
                status["cascade"] = {
                    "threshold": self.cascade_threshold,
                    "effective_threshold":
                        self._effective_cascade_threshold(),
                    "scored": self._cascade_scored,
                    "rejected": self._cascade_rejected,
                }
            if self.tracker is not None:
                status["tracks"] = self.tracker.stats()
            self.connector.publish(STATUS_TOPIC, status)

    # ---- lifecycle ----

    def start(self, warmup: bool = True) -> None:
        if self._thread is not None:
            return
        if warmup:
            self.warmup()
        # Install the dispatch fault boundary on the pipeline AFTER warmup:
        # the warmup compile must never consume a scripted chaos fault (or
        # randomly fail under soak rates) — only real serving batches cross
        # the boundary. stop() uninstalls, so a shared pipeline leaks no
        # injector into the next service built on it.
        if self._faults is not None:
            self.pipeline.fault_injector = self._faults
        self._running = True
        self._crashed = False
        self._loop_progress_t = None
        if self.ingest is not None:
            # Decode workers feed the same intake continuation the
            # connector thread uses; failures settle through the ledger.
            self.ingest.start(sink=self._intake_decoded,
                              on_error=self._decode_failed)
        self.connector.start()
        if self.state is not None:
            # Background durability ticker: watermarks + recovery probe
            # keep running even when the serving loop sits behind a slow
            # fsync (exactly the moments the monitor exists for).
            dur = getattr(self.state, "durability", None)
            if dur is not None:
                dur.start()
        if self._use_worker:
            self._blocker = _ReadbackBlocker()
            self._worker = threading.Thread(target=self._readback_thread,
                                            daemon=True,
                                            name="ocvf-readback-worker")
            self._worker.start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def warmup(self) -> None:
        """Compile the serving + enrolment graphs before frames arrive, so
        the first batch and the first enroll command pay no compile stall.
        Every bucket of the dispatch ladder is compiled — a partial batch
        at any ladder size must never hit a mid-serving XLA compile."""
        t0 = time.perf_counter()
        prewarm = getattr(self.pipeline, "prewarm_batch_shapes", None)
        if prewarm is not None:
            prewarm(self._bucket_ladder, self.batcher.frame_shape,
                    self.batcher.dtype)
        else:
            # Pipelines without the helper (e.g. TwoStagePipeline) still
            # get every ladder size executed once.
            for bucket in self._bucket_ladder:
                zeros = np.zeros((bucket, *self.batcher.frame_shape),
                                 self.batcher.dtype)
                out = self.pipeline.recognize_batch_packed(zeros)
                if hasattr(out, "block_until_ready"):
                    out.block_until_ready()  # ocvf-lint: boundary=host-sync -- warmup precedes start(): blocking until every ladder bucket is compiled is the contract
        chunk = np.zeros((self._enrol_chunk, *self.pipeline.face_size), np.float32)
        emb = self._run_embed_chunk(self.pipeline.embed_params, chunk)
        if hasattr(emb, "block_until_ready"):
            emb.block_until_ready()  # ocvf-lint: boundary=host-sync -- warmup precedes start(); the enrolment graph must be compiled before the first enroll command
        self.metrics.observe(mn.WARMUP, time.perf_counter() - t0)
        # Arm the recompile watchdog: from here on, a serving dispatch
        # that misses the jit cache is a mid-serving XLA compile the
        # prewarmed ladder was built to prevent.
        self._warmed = True

    def drain(self, timeout: float = 120.0) -> bool:
        """Block until every accepted frame has been batched, computed, AND
        published (or timeout). Call at end-of-stream BEFORE stop() —
        stop() tears the loop down promptly and discards whatever is still
        queued, which is right for Ctrl-C but wrong for a finite stream.
        Event-driven against the completion condition variable; the wait
        tick only bounds how often the batcher's pending count re-checks."""
        deadline = time.monotonic() + timeout
        with self._inflight_cv:
            while time.monotonic() < deadline:
                # Ingest idle FIRST: a decode worker counts busy until
                # its sink (the batcher put) returns, so once idle reads
                # True no frame can still be in transit toward the
                # batcher checks below. delivered == completed covers
                # popped-but-undispatched batches, the in-flight queue,
                # AND publish-in-progress (completed is bumped only
                # after _publish returns).
                if ((self.ingest is None or self.ingest.idle())
                        and self.batcher.pending == 0
                        and self.batcher.delivered_batches == self._completed_batches):
                    return True
                self._inflight_cv.wait(timeout=self._drain_poll_s)
        return False

    def stop(self) -> None:
        self._running = False
        self._flush_rejections(force=True)
        if self.state is not None:
            dur = getattr(self.state, "durability", None)
            if dur is not None:
                dur.stop()
        if self.ingest is not None:
            self.ingest.stop()
        self.batcher.close()
        with self._inflight_cv:
            self._inflight_cv.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None
        worker = self._worker
        if worker is not None:
            # The worker finishes the remaining in-flight batches itself
            # (each wait bounded by that batch's readback deadline), then
            # exits; a worker still alive after the join is bounded-waiting
            # on a deadline and will finish its own drain.
            worker.join(timeout=5.0)
            self._worker = None
        if (not self._use_worker
                and (thread is None or not thread.is_alive())):
            # Fallback path: final materialize only once the loop thread is
            # truly gone — two threads force-draining the same deque could
            # pair one batch's results with another's metadata.
            self._drain(force=True)
        if self._faults is not None and getattr(
                self.pipeline, "fault_injector", None) is self._faults:
            self.pipeline.fault_injector = None
        self.connector.stop()

    # ---- the serving loop ----

    @property
    def loop_crashed(self) -> bool:
        """True when an exception escaped a serving-side thread (the
        dispatch loop or the readback worker) and killed it
        (``ServiceSupervisor`` watches this flag)."""
        return self._crashed

    @property
    def loop_staleness_s(self) -> float:
        """Seconds since the serving loop last completed a queue pop —
        the loop_liveness gauge SLO's probe (``runtime.slo``). 0.0 while
        the service is stopped or the loop has not reached its first
        iteration yet (startup is covered by the bounded backend probe,
        not this signal)."""
        if not self._running or self._loop_progress_t is None:
            return 0.0
        return max(0.0, time.monotonic() - self._loop_progress_t)

    def restart_pending(self) -> bool:
        """True when the crash flag is up AND a serving-side thread has
        actually exited — i.e. ``restart_loop`` would act rather than
        no-op. The supervisor polls this instead of inspecting threads:
        a flag raised while the thread is still unwinding (slow 'crashed'
        status subscriber) must not burn phantom restarts."""
        if not self._crashed or not self._running:
            return False
        if self._thread is not None and not self._thread.is_alive():
            return True
        return (self._use_worker and self._worker is not None
                and not self._worker.is_alive())

    def restart_loop(self) -> None:
        """Restart crashed serving-side threads (supervisor path): whichever
        of the dispatch loop / readback worker died is respawned; a thread
        still alive is left untouched. Batch accounting needs no resync —
        every crash path settles its own popped batch before propagating
        (see ``_serve_one`` / ``_readback_loop``)."""
        if not self._running or self._thread is None:
            return
        serve_dead = not self._thread.is_alive()
        worker_dead = (self._use_worker and self._worker is not None
                       and not self._worker.is_alive())
        if not serve_dead and not worker_dead:
            return  # not actually crashed
        self._crashed = False
        if worker_dead:
            self._blocker = _ReadbackBlocker()
            self._worker = threading.Thread(target=self._readback_thread,
                                            daemon=True,
                                            name="ocvf-readback-worker")
            self._worker.start()
        if serve_dead:
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        try:
            self._serve_loop()
        except Exception:  # noqa: BLE001 — flag the crash for the supervisor
            logging.getLogger(__name__).exception("serving loop crashed")
            self.metrics.incr(mn.LOOP_CRASHES)
            self._crashed = True
            self._publish_status({"status": "crashed"})

    def _serve_loop(self) -> None:
        while self._running:
            batch = self.batcher.get_batch(block=True)
            # Liveness stamp: placed AFTER the pop so a loop wedged
            # anywhere in the iteration body (dispatch, inflight wait,
            # publish) stops refreshing it and ``loop_staleness_s`` grows.
            self._loop_progress_t = time.monotonic()
            # Durable-state tick: a cheap WAL row-count/age threshold
            # check; when due it SPAWNS the checkpoint worker (snapshot +
            # write happen off-thread, single-flight) — dispatch never
            # blocks on a checkpoint.
            if self.state is not None:
                self.state.tick()
                # Degraded-durability tick: interval-gated disk watermark
                # refresh ONLY (probe=False by default — the recovery
                # probe is a blocking fsync against a disk known broken,
                # and it belongs to the monitor's background thread, not
                # this loop). The non-due path is one clock read.
                dur = getattr(self.state, "durability", None)
                if dur is not None:
                    dur.tick()
            # SLO tick: one clock read when not due; a full burn-rate
            # evaluation every interval_s (runtime.slo). Runs on batch
            # AND idle iterations so the health verdict keeps updating
            # when traffic stops — recovery is part of the signal.
            if self.slo is not None:
                self.slo.tick()
            # Read-replica tick: tail the shared WAL and apply new rows
            # between batches (interval-gated inside poll; the non-due
            # path is one clock read). A poll failure (disk blip on the
            # shared dir) must cost this poll, never the serving loop —
            # the lag gauges and SLO objective surface a replica that
            # stops advancing.
            if self.replica is not None:
                try:
                    self.replica.poll()
                except Exception:  # noqa: BLE001 — replication must not kill serving
                    logging.getLogger(__name__).exception(
                        "read-replica WAL poll failed")
                    self.metrics.incr(mn.REPLICATION_POLL_ERRORS)
            if batch is None:
                if not self._running:
                    break
                # Idle tick: an empty queue means zero queue wait — feed
                # the brownout EWMA so it recovers even when the flood
                # stops dead (no batches would otherwise update it) — and
                # announce any rejections still pending from a flood that
                # ended mid-aggregation-window.
                self._note_queue_wait(0.0)
                self._flush_rejections()
                if not self._use_worker:
                    self._drain()
                continue
            self._serve_one(batch)
        if not self._use_worker:
            self._drain(force=True)

    def _serve_one(self, batch) -> None:
        frames, metas, count = batch.frames, batch.metas, batch.count
        trace_ids = batch.trace_ids
        tracer = self.tracer
        # Batch trace: the coalescing ancestor every traced frame in this
        # batch points at (queue_wait spans carry ``batch=<this id>``);
        # batch-level spans (dispatch/ready_wait/publish) ride it. Never
        # sampled independently — it exists iff any member frame is traced.
        batch_tid = (tracer.new_trace()
                     if tracer is not None and any(trace_ids) else 0)
        t0 = time.perf_counter()
        # Queue-wait: frame enqueue -> batch pop. The batching-delay
        # term of the end-to-end latency decomposition (continuous-batching
        # deadline + waiting for batch_size peers), measured per frame —
        # and the brownout controller's load signal (batch mean).
        now_mono = time.monotonic()
        for ts, tid in zip(batch.enqueue_ts, trace_ids):
            self.metrics.observe(mn.QUEUE_WAIT, now_mono - ts)
            if tid:
                tracer.emit(tid, "queue_wait", topic=FRAME_TOPIC, t0=ts,
                            dur=now_mono - ts, batch=batch_tid)
        if batch.enqueue_ts:
            self._note_queue_wait(
                sum(now_mono - ts for ts in batch.enqueue_ts)
                / len(batch.enqueue_ts))
        # Max-brownout ladder cap: trim an oversized batch down to one
        # small fast device call; the trimmed (newest) frames are shed
        # with an explicit reason, not silently truncated.
        cap = self._brownout_bucket_cap()
        if cap is not None and count > cap:
            self.metrics.incr(mn.FRAMES_DROPPED_BROWNOUT, count - cap)
            self._trace_settle(trace_ids[cap:count],
                               mn.FRAMES_DROPPED_BROWNOUT,
                               "dispatch.brownout_trim", batch=batch_tid)
            self._journal_drop("brownout", self._drop_entries(
                metas[cap:count], batch.enqueue_ts[cap:count],
                trace_ids[cap:count], "dispatch.brownout_trim"),
                level=self._brownout_level)
            count = cap
        accounted = False
        try:
            # Track-cache gate (ISSUE 17), BEFORE the cascade: a lookup
            # is pure host work, cheaper than the stage-1 device pass, so
            # cache hits save both stages. Hits settle as
            # ``completed_cached`` (published with the cached identities,
            # never dispatched); the survivors compact toward the staging
            # buffer's front exactly like the cascade's, so the rungs
            # below dispatch only what actually needs device work.
            if count and self.tracker is not None:
                stretch = self._track_reverify_stretch()
                track_ver = getattr(self.pipeline.gallery,
                                    "embedder_version", None)
                if track_ver is not None:
                    track_ver = int(track_ver)
                # Full registry stamp when the registry is wired: a
                # detector/cascade cutover invalidates cached verdicts
                # exactly like an embedder cutover (opaque equality).
                track_ver = self._model_stamp(track_ver)
                cached = []
                keep_list = []
                for i in range(count):
                    hit = self._track_lookup(metas[i], frames[i],
                                             track_ver, stretch)
                    if hit is not None:
                        cached.append((metas[i], batch.enqueue_ts[i],
                                       trace_ids[i], batch.priorities[i],
                                       hit))
                    else:
                        keep_list.append(i)
                if cached:
                    keep_idx = np.asarray(keep_list, dtype=np.intp)
                    kept = len(keep_idx)
                    if kept:
                        frames[:kept] = frames[keep_idx]
                    metas = ([metas[i] for i in keep_list]
                             + [None] * (len(metas) - kept))
                    batch = batch._replace(
                        metas=metas, count=kept,
                        enqueue_ts=[batch.enqueue_ts[i] for i in keep_list],
                        trace_ids=[trace_ids[i] for i in keep_list],
                        priorities=[batch.priorities[i] for i in keep_list])
                    trace_ids = batch.trace_ids
                    count = kept
                    if batch_tid:
                        tracer.emit(batch_tid, "track_cache",
                                    topic=tracing.BATCH_TOPIC,
                                    frames=kept + len(cached),
                                    hits=len(cached))
                    self._complete_cached(cached, batch_tid)
                    if not count:
                        # Whole batch answered from the cache: no device
                        # work at all this iteration.
                        self.metrics.incr(mn.TRACK_BATCH_EXITS)
                        if batch_tid:
                            tracer.emit(batch_tid, "dispatch",
                                        topic=tracing.BATCH_TOPIC,
                                        dur=time.perf_counter() - t0,
                                        bucket=0, frames=0,
                                        exit="track_cache",
                                        brownout=self._brownout_level)
                        accounted = True
                        self._mark_completed()
                        self.batcher.recycle(frames)
                        self.batcher.report_service_time(
                            time.perf_counter() - t0)
                        return
            # Stage-1 cascade gate (ISSUE 13): score the whole batch at
            # its ladder rung, settle face-free frames as
            # ``completed_empty`` (published with an empty face list,
            # never dispatched to detect->crop->embed->match), and
            # compact survivors toward the staging buffer's front so the
            # bucket slice below dispatches the smallest rung that fits
            # what is left. Settlement ordering keeps the crash handler
            # exact: ``count`` shrinks to the survivors BEFORE the
            # rejected frames settle, so a crash anywhere after still
            # settles every frame exactly once.
            if count and self._cascade_active:
                keep = self._cascade_keep_mask(frames, count, batch_tid)
                if keep is not None and not keep.all():
                    keep_idx = np.flatnonzero(keep)
                    rejected = [(metas[i], batch.enqueue_ts[i],
                                 trace_ids[i], batch.priorities[i])
                                for i in np.flatnonzero(~keep)]
                    kept = len(keep_idx)
                    if kept:
                        # Fancy-index gather copies survivors out before
                        # the front rows are overwritten: safe in-place
                        # compaction of the pooled staging buffer.
                        frames[:kept] = frames[keep_idx]
                    metas = ([metas[i] for i in keep_idx]
                             + [None] * (len(metas) - kept))
                    batch = batch._replace(
                        metas=metas, count=kept,
                        enqueue_ts=[batch.enqueue_ts[i] for i in keep_idx],
                        trace_ids=[trace_ids[i] for i in keep_idx],
                        priorities=[batch.priorities[i] for i in keep_idx])
                    trace_ids = batch.trace_ids
                    count = kept
                    self._complete_empty(rejected, batch_tid)
                    if not count:
                        # Zero survivors: the whole batch exits at stage
                        # 1 — no stage-2 dispatch at all, THE early-exit
                        # win. The dispatch span records the exit stage
                        # so PR 8 attribution stays honest.
                        self.metrics.incr(mn.CASCADE_BATCH_EXITS)
                        if batch_tid:
                            tracer.emit(batch_tid, "dispatch",
                                        topic=tracing.BATCH_TOPIC,
                                        dur=time.perf_counter() - t0,
                                        bucket=0, frames=0,
                                        exit="cascade",
                                        brownout=self._brownout_level)
                        accounted = True
                        self._mark_completed()
                        # The stage-1 scores readback completed, which
                        # fences the buffer's H2D read: safe to recycle.
                        self.batcher.recycle(frames)
                        self.batcher.report_service_time(
                            time.perf_counter() - t0)
                        return
            # Bucketed dispatch: slice the padded staging array down to the
            # smallest warmed ladder size that fits the real frames — a
            # view, not a copy, so steady state allocates nothing.
            bucket = self._pick_bucket(count)
            view = frames[:bucket] if bucket < len(frames) else frames
            if batch_tid and self.ingest is not None:
                # Ingest provenance: which staging rung carried the batch
                # and which bucket it dispatches at (rung >= bucket; the
                # ring hands the smallest rung that fits).
                tracer.emit(batch_tid, "stage", topic=tracing.BATCH_TOPIC,
                            rung=len(frames), bucket=bucket, frames=count)
            # Embedder-version stamp captured AT DISPATCH: the batch's
            # scores are computed against the gallery data this dispatch
            # reads, so its published results carry the version serving
            # when the batch entered the device — a cutover swapping the
            # gallery later never back-stamps an in-flight batch. (The
            # version moves monotonically and exactly once per rollout,
            # so per-replica result stamps form a clean old->new prefix —
            # the no-mixed-scores assertion chaos_soak checks.)
            gallery_ver = getattr(self.pipeline.gallery,
                                  "embedder_version", None)
            if gallery_ver is not None:
                gallery_ver = int(gallery_ver)
            # Registry-wired services widen the dispatch stamp to the
            # full (role, version) tuple HERE, for the same reason: a
            # registry cutover landing while this batch is on device
            # must never back-stamp its results with the new model set.
            gallery_ver = self._model_stamp(gallery_ver)
            packed = self._dispatch_with_retry(view, batch_tid)
            if packed is None:
                # Retries exhausted or the error was permanent (poisoned
                # batch): abandoned, not published — but still completed
                # for drain() accounting (and an explicit per-frame drop
                # in the admission ledger + journal).
                self.metrics.incr(mn.FRAMES_FAILED, count)
                self._trace_settle(trace_ids[:count], mn.FRAMES_FAILED,
                                   "dispatch.abandoned", batch=batch_tid)
                self._journal_drop("failed", self._drop_entries(
                    metas[:count], batch.enqueue_ts[:count],
                    trace_ids[:count], "dispatch.abandoned"))
                self._mark_completed()
                accounted = True
                if self.ingest is not None:
                    # An attempt's explicit async upload may still hold a
                    # pending read of this staging buffer — forfeit (the
                    # ring heals) instead of recirculating it.
                    self.batcher.forfeit(frames)
                else:
                    self.batcher.recycle(frames)
                return
            # Host-side dispatch cost (H2D + trace-cache hit + async enqueue
            # — never device compute, which is async from here).
            t_disp = time.perf_counter()
            self.metrics.observe(mn.DISPATCH, t_disp - t0)
            deadline = time.monotonic() + self.resilience.readback_deadline_s
            with self._inflight_cv:
                self._inflight.append((packed, frames, metas, count,
                                       batch.enqueue_ts, t0, t_disp, deadline,
                                       trace_ids, batch_tid,
                                       batch.priorities, gallery_ver))
                accounted = True
                self._inflight_cv.notify_all()
        except BaseException:
            if not accounted:
                # The popped batch dies with this crash; settle it so
                # drain()'s delivered==completed stays solvable after the
                # supervisor restarts the loop — and its frames land in
                # the ledger's crash bucket, not in limbo. The staging
                # buffer is forfeited, not recycled: the crash may have
                # left an async H2D read of it pending.
                self.metrics.incr(mn.FRAMES_DROPPED_CRASHED, count)
                self._trace_settle(trace_ids[:count],
                                   mn.FRAMES_DROPPED_CRASHED,
                                   "dispatch.crashed", batch=batch_tid)
                self.batcher.forfeit(frames)
                self._mark_completed()
            raise
        self.metrics.incr(mn.BATCHES_DISPATCHED)
        self.metrics.incr(mn.FRAMES_PROCESSED, count)
        # Dispatch provenance is read for the batch span AND the recompile
        # watchdog, so it is fetched regardless of tracing.
        info = getattr(self.pipeline, "last_dispatch_info", None) or {}
        if batch_tid:
            # Bucketed-dispatch provenance: bucket size, jit-cache verdict
            # and exact-vs-ivf matcher mode (the pipeline records both on
            # dispatch), plus the brownout level the batch served under
            # and the cascade exit stage (``full`` = stage 2 ran; a batch
            # that never got here carries ``exit="cascade"`` instead).
            tracer.emit(batch_tid, "dispatch", topic=tracing.BATCH_TOPIC,
                        dur=t_disp - t0, bucket=bucket, frames=count,
                        cache_hit=info.get("cache_hit"),
                        mode=info.get("mode"), exit="full",
                        brownout=self._brownout_level)
        if self._warmed and info.get("cache_hit") is False:
            # Recompile watchdog (see _note_recompile): a serving
            # dispatch missed the jit cache AFTER warmup compiled the
            # whole bucket ladder.
            self._note_recompile(bucket, count, info.get("mode"))
        if bucket < self.batcher.batch_size:
            self.metrics.incr(mn.BATCHES_BUCKETED)
        if self._use_worker:
            # Backpressure: beyond inflight_depth undrained batches, wait
            # for the readback worker to free a slot (it notifies the cv on
            # every pop) before popping more frames. The timeout only
            # bounds liveness re-checks (stop), never paces a healthy
            # pipeline. Deliberately NOT escaped on a worker crash: parking
            # here keeps the in-flight queue bounded until the supervisor
            # respawns the worker (or stop() clears _running).
            with self._inflight_cv:
                while (self._running
                       and len(self._inflight) > self.inflight_depth):
                    self._inflight_cv.wait(timeout=self._drain_poll_s)
        else:
            self._drain()

    def _mark_completed(self, n: int = 1) -> None:
        with self._inflight_cv:
            self._completed_batches += n
            self._inflight_cv.notify_all()

    def _dispatch_with_retry(self, frames, batch_tid: int = 0
                             ) -> Optional[Any]:
        """One batch through the device, honoring the resilience policy:
        transient failures retry with exponential backoff (the readback
        worker keeps draining while we wait), permanent ones abandon
        immediately, and ``degraded_after`` consecutive failed attempts
        publish degraded mode. Returns the dispatched (async) output, or
        None when the batch is abandoned (``batches_failed``). With the
        ingest subsystem, every ATTEMPT re-uploads the host staging view
        explicitly (uint8 across the wire, cast fused on device) — a
        donated device buffer from a failed attempt is never re-fed."""
        policy = self.resilience
        attempt = 0
        while True:
            try:
                send = frames
                if self.ingest is not None:
                    send, up_bytes, up_dur = self.ingest.upload(frames)
                    if batch_tid:
                        self.tracer.emit(batch_tid, "upload",
                                         topic=tracing.BATCH_TOPIC,
                                         dur=up_dur, bytes=up_bytes,
                                         dtype=str(frames.dtype))
                # Packed path: ONE output array -> one D2H readback per
                # batch (a tunneled backend charges ~100 ms per blocking
                # readback; five separate arrays measured 5x slower).
                packed = self.pipeline.recognize_batch_packed(send)
                packed.copy_to_host_async()
            except Exception as exc:  # noqa: BLE001 — classified below
                self.metrics.incr(mn.DISPATCH_FAILURES)
                self._consecutive_dispatch_failures += 1
                if (self._consecutive_dispatch_failures >= policy.degraded_after
                        and not self._degraded):
                    self._enter_degraded(exc)
                transient = is_transient_error(exc)
                if not transient or attempt >= policy.dispatch_retries:
                    logging.getLogger(__name__).exception(
                        "recognition batch abandoned (%s, attempt %d)",
                        "transient" if transient else "permanent", attempt)
                    self.metrics.incr(mn.BATCHES_FAILED)
                    return None
                self.metrics.incr(mn.DISPATCH_RETRIES)
                self._backoff_wait(policy.backoff(attempt))
                attempt += 1
                if not self._running:
                    self.metrics.incr(mn.BATCHES_FAILED)
                    return None
                continue
            if self._consecutive_dispatch_failures:
                self._consecutive_dispatch_failures = 0
            if self._degraded:
                self._exit_degraded()
            # Async-readback fault boundary (runtime.faults): may wrap the
            # output in a never-ready proxy — the hang-mode outage.
            if self._faults is not None:
                packed = self._faults.on_readback(packed)
            return packed

    def _backoff_wait(self, seconds: float) -> None:
        """Sleep in small slices, bailing promptly on stop(). On the
        fallback path this also drains in-flight readbacks (a retry storm
        must not let completed batches rot past their result consumers);
        with the worker the drain happens concurrently anyway."""
        deadline = time.monotonic() + seconds
        while self._running and time.monotonic() < deadline:
            if not self._use_worker:
                self._drain()
            time.sleep(min(0.01, max(0.0, deadline - time.monotonic())))

    # ---- degraded mode ----

    def _enter_degraded(self, exc: BaseException) -> None:
        self._degraded = True
        self.metrics.incr(mn.DEGRADED_TRANSITIONS)
        status = {
            "status": "degraded",
            "consecutive_failures": self._consecutive_dispatch_failures,
            "error": repr(exc),
        }
        if self.resilience.probe_backend_on_degraded:
            usable, reason = self._probe_backend()
            status["backend_usable"] = usable
            status["backend_reason"] = reason
            if not usable and self._cpu_fallback is not None:
                try:
                    self._cpu_fallback(self)
                    self.metrics.incr(mn.CPU_FALLBACKS)
                    status["cpu_fallback"] = True
                except Exception:  # noqa: BLE001 — fallback is best-effort
                    logging.getLogger(__name__).exception("cpu fallback failed")
                    status["cpu_fallback"] = False
        self._publish_status(status)

    def _exit_degraded(self) -> None:
        self._degraded = False
        self.metrics.incr(mn.DEGRADED_RECOVERIES)
        status = {"status": "recovered"}
        if self._embed_device is not None:
            # "Recovered" only in the sense that dispatches succeed again —
            # on the CPU-fallback pipeline, not the accelerator. Deploy
            # tooling must keep treating the job as degraded-capacity.
            status["on_cpu_fallback"] = True
        self._publish_status(status)

    def _publish_status(self, status: Dict[str, Any]) -> None:
        """Status publishes run on serving-side threads and subscribers are
        arbitrary app code — a raising status consumer must degrade to a
        logged error, never crash the loop it is reporting on."""
        try:
            self.connector.publish(STATUS_TOPIC, status)
        except Exception:  # noqa: BLE001 — transport/subscriber may be down
            logging.getLogger(__name__).exception("status publish failed")

    def _probe_backend(self) -> tuple:
        """Bounded verdict on the accelerator (never hangs): the injected
        fn for tests, else utils.backend_probe's subprocess probe with
        allow_cpu=False — a silent JAX CPU fallback must read as "backend
        dead", not "healthy", or the CPU-fallback hook never fires."""
        if self._backend_probe_fn is not None:
            return self._backend_probe_fn()
        from opencv_facerecognizer_tpu.utils.backend_probe import (
            probe_for_recovery,
        )

        return probe_for_recovery(timeout_s=self.resilience.probe_timeout_s)

    def _dead_letter(self, count: int, metas: Optional[List[Any]] = None,
                     enqueue_ts: Optional[List[float]] = None,
                     trace_ids: Optional[List[int]] = None,
                     batch: int = 0) -> None:
        """Abandon a batch whose readback outlived its deadline: counted,
        announced, completed — never blocked on (SURVEY.md §5.3: an
        unhealthy accelerator degrades the job, never wedges it). The
        status message carries the dead frames' ids (their ``meta``) and
        enqueue timestamps so producers can retry, and the same entries
        land in the dead-letter journal. A dead-letter is also a
        flight-recorder trigger: the span rings are dumped (rate-limited)
        and the dump path rides the journal record, so "what was in
        flight when this batch died" is answerable after the fact."""
        self.metrics.incr(mn.BATCHES_DEAD_LETTERED)
        self.metrics.incr(mn.FRAMES_DEAD_LETTERED, count)
        self._mark_completed()
        # Slice every provenance list to ``count``: metas is the PADDED
        # [batch_size] list, and after a brownout trim the enqueue_ts/
        # trace_ids lists still hold the trimmed (already settled) frames
        # beyond count — journaling or re-settling those would invent
        # phantom rows / duplicate terminal spans.
        metas = (list(metas[:count]) if metas is not None
                 else [None] * count)
        enqueue_ts = enqueue_ts[:count] if enqueue_ts is not None else None
        trace_ids = trace_ids[:count] if trace_ids is not None else None
        self._trace_settle(trace_ids if trace_ids is not None else (),
                           mn.FRAMES_DEAD_LETTERED, "readback.dead_letter",
                           batch=batch)
        dump = None
        if self.tracer is not None:
            if batch:
                self.tracer.emit(batch, "dead_letter",
                                 topic=tracing.BATCH_TOPIC, frames=count)
            dump = self.tracer.dump("dead_letter",
                                    extra={"frames": count,
                                           "ledger": self.ledger()})
        entries = self._drop_entries(metas, enqueue_ts, trace_ids,
                                     "readback.dead_letter")
        extra = {"dump": dump} if dump else {}
        self._journal_drop("dead_letter", entries, **extra)
        self._publish_status({
            "status": "dead_letter",
            "frames": count,
            "frame_ids": [e["meta"] for e in entries],
            "enqueued_at": [e["enqueue_ts"] for e in entries],
        })

    @staticmethod
    def _is_ready(packed) -> bool:
        """Non-blocking readiness; backends without ``is_ready`` report
        ready and fall back to the blocking materialize (old behavior).
        A RAISING is_ready (outage surfacing at the readback side) also
        reports ready: the materialize then surfaces the error where
        ``_complete_head`` dead-letters it instead of crashing a thread."""
        try:
            return bool(packed.is_ready())
        except (AttributeError, NotImplementedError):
            return True
        except Exception:  # ocvf-lint: disable=swallowed-exception -- deliberate defer: reporting ready makes materialize re-raise on the classifying path, where _complete_head dead-letters with full accounting
            return True

    # ---- the readback worker (threaded path) ----

    def _readback_thread(self) -> None:
        try:
            self._readback_loop()
        except Exception:  # noqa: BLE001 — flag the crash for the supervisor
            logging.getLogger(__name__).exception("readback worker crashed")
            self.metrics.incr(mn.LOOP_CRASHES)
            self._crashed = True
            self._publish_status({"status": "crashed"})

    def _readback_loop(self) -> None:
        """Drain the in-flight queue in dispatch order: block on each
        batch's device array (bounded by its readback deadline), then
        materialize + publish. Runs until stopped AND the queue is empty,
        so stop() after drain() loses nothing. The entry stays at the head
        of the deque while we wait — the backpressure slot is only freed
        (cv notified) once its batch's device round-trip actually ended."""
        while True:
            with self._inflight_cv:
                while self._running and not self._inflight:
                    self._inflight_cv.wait(timeout=self._drain_poll_s)
                if not self._inflight:
                    if not self._running:
                        return
                    continue
                packed, frames, metas, count, enqueue_ts, t0, t_disp, \
                    deadline, trace_ids, batch_tid, priorities, \
                    gallery_ver = self._inflight[0]
            try:
                ready = self._await_ready(packed, deadline)
            except Exception:  # noqa: BLE001 — outage at the readback side
                # A transient backend error surfacing here must cost this
                # batch, not the worker thread (a crash loop would burn
                # the supervisor's bounded restarts on an outage the
                # dispatch side survives via retry/degraded mode).
                logging.getLogger(__name__).exception("readback wait failed")
                self.metrics.incr(mn.READBACK_ERRORS)
                ready = False
            with self._inflight_cv:
                self._inflight.popleft()
                self._inflight_cv.notify_all()
            if not ready:
                # Do NOT recycle the staging buffer: the batch's device
                # round-trip never completed, so the backend's async H2D
                # read of this exact host array may still be pending —
                # reusing it would race the outage we just survived. The
                # legacy pool refills from completed batches; a bounded
                # staging ring is told explicitly (forfeit) so it may
                # heal with one replacement allocation.
                self.batcher.forfeit(frames)
                self._dead_letter(count, metas, enqueue_ts, trace_ids,
                                  batch_tid)
                continue
            self._complete_head(packed, frames, metas, count, enqueue_ts,
                                t0, t_disp, trace_ids, batch_tid, priorities,
                                gallery_ver)

    def _await_ready(self, packed, deadline: float) -> bool:
        """Wait for one batch's transfer, bounded by its deadline. Returns
        False when the deadline won (caller dead-letters). Event-driven:
        the sacrificial blocker thread performs ``block_until_ready`` so a
        hang-mode outage costs one abandoned daemon thread, not a wedged
        worker — and a healthy readback never pays an ``is_ready`` poll
        (the tunnel charges ~100 ms per sync-poll)."""
        if not hasattr(packed, "block_until_ready"):
            return True  # plain host value (already materialized)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return self._is_ready(packed)
        blocker = self._blocker
        if blocker is None:
            blocker = self._blocker = _ReadbackBlocker()
        outcome = blocker.block(packed, remaining)
        if outcome == "ready":
            return True
        if outcome == "timeout":
            # The blocker may be wedged in native code on the hung array —
            # abandon it; the next batch gets a fresh one.
            self._blocker = _ReadbackBlocker()
            return False
        # "raised": either a proxy that refuses to block (the injected
        # stuck readback raises instead of hanging the suite) or a failed
        # computation (ready-with-error). Bounded is_ready polling sorts
        # them out: never-ready dead-letters at the deadline; a failed
        # computation reports ready and materializes its error upstream.
        while self._running and time.monotonic() < deadline:
            if self._is_ready(packed):
                return True
            time.sleep(self._readback_poll_s)
        return self._is_ready(packed)

    # ---- the inline drain (fallback non-threaded path) ----

    def _drain(self, force: bool = False) -> None:
        """Materialize finished batches inline (``readback_worker=False``).
        A not-ready head batch past its readback deadline is dead-lettered;
        when over depth (or forced) the wait for the head is a bounded
        ``is_ready`` poll (tick: ``readback_poll_s``) capped by that same
        deadline — never an unbounded blocking readback a hang-mode outage
        could wedge."""
        while self._inflight:
            packed, frames, metas, count, enqueue_ts, t0, t_disp, deadline, \
                trace_ids, batch_tid, priorities, gallery_ver \
                = self._inflight[0]
            ready = self._is_ready(packed)
            if not ready:
                if time.monotonic() >= deadline:
                    # No recycle: the incomplete round-trip may still hold
                    # an async read on this staging buffer (see the worker
                    # path's dead-letter note). Forfeit so a ring heals.
                    self._pop_inflight_head()
                    self.batcher.forfeit(frames)
                    self._dead_letter(count, metas, enqueue_ts, trace_ids,
                                      batch_tid)
                    continue
                if not (force or len(self._inflight) > self.inflight_depth):
                    break
                # Over depth / forced: poll until ready or deadline. The
                # poll IS the readback wait — it lands in ready_wait below.
                while not ready and time.monotonic() < deadline:
                    time.sleep(self._readback_poll_s)
                    ready = self._is_ready(packed)
                if not ready:
                    self._pop_inflight_head()
                    self.batcher.forfeit(frames)  # no recycle; ring heals
                    self._dead_letter(count, metas, enqueue_ts, trace_ids,
                                      batch_tid)
                    continue
            self._pop_inflight_head()
            self._complete_head(packed, frames, metas, count, enqueue_ts,
                                t0, t_disp, trace_ids, batch_tid, priorities,
                                gallery_ver)

    def _complete_head(self, packed, frames, metas, count, enqueue_ts,
                       t0, t_disp, trace_ids=(), batch_tid=0,
                       priorities=(), gallery_ver=None) -> None:
        """Materialize + publish one POPPED batch and settle its accounting
        — the shared tail of the readback worker and the fallback drain
        (the two paths must stay behaviorally identical apart from
        scheduling; bench_serving's overlap_comparison relies on it).

        Three invariants live here, once:
        - a materialize failure (an outage error riding the result array)
          dead-letters the batch (``readback_errors``) instead of crashing
          the thread — the readback-side mirror of the dispatch retry
          classification;
        - ``ready_wait`` is stamped AFTER ``np.asarray``: on the blocking
          (over-depth/forced) fallback path the conversion IS the readback
          (the tunneled backend's ~100 ms sync-poll floor lands in this
          term — compare bench.py's chained-diff device ms/batch to see
          how much is tunnel vs chip), and it must never leak into
          'publish';
        - a crash escaping the publish path still settles
          ``_completed_batches`` first, so drain() stays solvable after
          the supervisor restarts the thread.
        """
        try:
            arr = np.asarray(packed)  # ocvf-lint: boundary=host-sync -- THE one per-batch materialize (PR 2's packed single-readback design); runs on the readback worker / post-is_ready drain, never ahead of readiness
        except Exception:  # noqa: BLE001 — outage error carried by the array
            logging.getLogger(__name__).exception(
                "readback materialize failed")
            self.metrics.incr(mn.READBACK_ERRORS)
            # completed++, no recycle (see above); forfeit so a ring heals
            self.batcher.forfeit(frames)
            self._dead_letter(count, metas, enqueue_ts, trace_ids, batch_tid)
            return
        ready_dur = time.perf_counter() - t_disp
        self.metrics.observe(mn.READY_WAIT, ready_dur)
        if batch_tid:
            # Dispatch -> readback-complete: the device round-trip term
            # (perf_counter durations are epoch-free, so the span rides a
            # fresh monotonic stamp minus the measured duration).
            self.tracer.emit(batch_tid, "ready_wait",
                             topic=tracing.BATCH_TOPIC, dur=ready_dur,
                             frames=count)
        t_pub = time.perf_counter()
        try:
            self._publish(arr, frames, metas, count, trace_ids, batch_tid,
                          gallery_ver)
        except BaseException:
            self._mark_completed()
            # The readback COMPLETED before publish, so the staging
            # buffer is safe to recirculate — and with a bounded ring it
            # MUST be: dropping it here would shrink the ring by one per
            # publish crash with no heal credit, until admission sheds
            # everything against a ring that can never refill.
            self.batcher.recycle(frames)
            raise
        self._mark_completed()
        now = time.perf_counter()
        if batch_tid:
            self.tracer.emit(batch_tid, "publish", topic=tracing.BATCH_TOPIC,
                             dur=now - t_pub, frames=count)
        self.metrics.observe(mn.PUBLISH, now - t_pub)
        self.metrics.observe(mn.BATCH_LATENCY, now - t0)
        # Per-frame end-to-end latency (batcher enqueue -> published):
        # the SLO layer's headline histogram, split by priority class so
        # the interactive objective never averages in bulk traffic.
        # enqueue_ts stamps are monotonic; one clock read covers the run.
        if enqueue_ts:
            now_mono = time.monotonic()
            for i in range(min(count, len(enqueue_ts))):
                self._observe_e2e(
                    enqueue_ts[i],
                    priorities[i] if i < len(priorities)
                    else PRIORITY_INTERACTIVE + 1,
                    now_mono)
        # Feed the continuous batcher's adaptive deadline with the
        # realized downstream time (pop -> published).
        self.batcher.report_service_time(now - t0)
        self.batcher.recycle(frames)

    def _pop_inflight_head(self) -> None:
        with self._inflight_cv:
            self._inflight.popleft()
            self._inflight_cv.notify_all()

    def _publish(self, packed, frames, metas, count, trace_ids=(),
                 batch_tid=0, gallery_ver=None) -> None:
        from opencv_facerecognizer_tpu.parallel.pipeline import unpack_result

        published = 0
        rollout = self.rollout
        registry_swap = self.registry_swap
        # ``gallery_ver`` is the DISPATCH-time model stamp: a plain int
        # embedder version, or the full registry (role, version) tuple
        # when the registry is wired. Split once — every published row
        # and tracker verdict in this batch carries the same stamp, so a
        # cutover landing mid-publish never splits a batch.
        stamp = gallery_ver
        emb_ver, reg_roles = self._stamp_fields(stamp)
        try:
            result = unpack_result(np.asarray(packed), self.pipeline.top_k)  # no-op if already host
            boxes = result.boxes
            det_scores = result.det_scores
            valid = result.valid
            labels = result.labels
            sims = result.similarities
            for i in range(count):
                faces = []
                for j in range(boxes.shape[1]):
                    if not valid[i, j]:
                        continue
                    sim = float(sims[i, j, 0])
                    label = int(labels[i, j, 0])
                    known = sim >= self.similarity_threshold and label >= 0
                    name = (
                        self.subject_names[label]
                        if known and label < len(self.subject_names)
                        else ("unknown" if not known else str(label))
                    )
                    y0, x0, y1, x1 = (float(v) for v in boxes[i, j])
                    faces.append({
                        "box": [x0, y0, x1, y1],  # x-first, like the reference API
                        "detection_score": float(det_scores[i, j]),
                        "label": label if known else -1,
                        "name": name,
                        "similarity": sim,
                    })
                self._maybe_collect_enrolment(frames[i], faces)
                payload = {"meta": metas[i], "faces": faces}
                if emb_ver is not None:
                    # The embedder version the batch was SCORED against
                    # (captured + int-coerced at dispatch) — consumers and
                    # the rollout chaos scenario key the no-mixed-scores
                    # invariant on this stamp.
                    payload["embedder_version"] = emb_ver
                if reg_roles is not None:
                    # The full registry stamp (dispatch-time): the chaos
                    # registry scenario keys its no-unfenced-version
                    # assertion on this dict.
                    payload["registry"] = reg_roles
                self.connector.publish(RESULT_TOPIC, payload)
                published += 1
                self.metrics.incr(mn.FACES_FOUND, len(faces))
                if self.tracker is not None:
                    # Every FULL published result re-verifies its
                    # stream's tracks (association + identity
                    # cross-check + miss aging). Fail open: a tracker
                    # bug costs future cache wins, never this result.
                    key = self._track_stream_key(metas[i])
                    if key is not None:
                        try:
                            self.tracker.update(
                                key, faces, frames[i],
                                embedder_version=stamp)
                        except Exception:  # noqa: BLE001 — cache only
                            logging.getLogger(__name__).exception(
                                "tracker update failed")
                            self.metrics.incr(mn.TRACK_ERRORS)
                if rollout is not None and faces:
                    # Dual-score parity sampling (rate-limited + copied
                    # inside; scored on the rollout thread). A coordinator
                    # bug must cost a counter, never the publish path.
                    try:
                        rollout.offer_live(frames[i], faces)
                    except Exception:  # noqa: BLE001 — observation only
                        logging.getLogger(__name__).exception(
                            "rollout live-parity offer failed")
                        self.metrics.incr(mn.ROLLOUT_OBSERVE_ERRORS)
                if registry_swap is not None:
                    # Detection-parity sampling for an in-flight registry
                    # swap: whole frames + the serving detector's verdict
                    # boxes (the publish path already paid for them), so
                    # the candidate detector is scored against live
                    # traffic including face-free frames. Same fail-open
                    # contract as the rollout offer.
                    try:
                        registry_swap.offer_live(frames[i], faces)
                    except Exception:  # noqa: BLE001 — observation only
                        logging.getLogger(__name__).exception(
                            "registry live-parity offer failed")
                        self.metrics.incr(mn.REGISTRY_OBSERVE_ERRORS)
        finally:
            # Ledger settlement happens HERE, per batch, whatever exits:
            # frames that made it out are completed; on a crash escaping
            # mid-batch the remainder lands in the crash bucket (the
            # publishing thread dies, the supervisor restarts it — the
            # frames must not stay in limbo between those events). The
            # terminal spans mirror the same split exactly.
            self.metrics.incr(mn.FRAMES_COMPLETED, published)
            self._trace_settle(trace_ids[:published],
                               tracing.OUTCOME_COMPLETED, "publish",
                               batch=batch_tid)
            if published < count:
                self.metrics.incr(mn.FRAMES_DROPPED_CRASHED, count - published)
                self._trace_settle(trace_ids[published:count],
                                   mn.FRAMES_DROPPED_CRASHED,
                                   "publish.crashed", batch=batch_tid)

    # ---- enrolment (interactive-trainer protocol) ----

    def _maybe_collect_enrolment(self, frame: np.ndarray, faces: List[dict]) -> None:
        with self._enrol_lock:
            enrolment = self._enrolment
        if enrolment is None or not faces:
            return
        best = max(faces, key=lambda f: f["detection_score"])
        x0, y0, x1, y1 = (int(round(v)) for v in best["box"])
        h, w = frame.shape
        y0, y1 = max(0, y0), min(h, y1)
        x0, x1 = max(0, x0), min(w, x1)
        if y1 - y0 < 4 or x1 - x0 < 4:
            return
        # COPY, not a view: the frame lives in a pooled staging buffer that
        # is recycled (and overwritten) as soon as this batch completes.
        enrolment.crops.append(frame[y0:y1, x0:x1].copy())
        if len(enrolment.crops) >= enrolment.needed:
            with self._enrol_lock:
                self._enrolment = None
            # Off the serving threads: the embed + gallery install must not
            # stall frame batches (reload-without-drop, SURVEY.md §5.3).
            threading.Thread(
                target=self._finish_enrolment, args=(enrolment,), daemon=True
            ).start()

    def _finish_enrolment(self, enrolment: _Enrolment) -> None:
        from opencv_facerecognizer_tpu.ops import image as image_ops

        face_size = self.pipeline.face_size
        # Version fence stamp, read BEFORE the embed: these crops are
        # about to be embedded by the CURRENT model — if a rollout
        # cutover swaps the space before the WAL append below, the
        # lifecycle refuses the stale-space rows closed
        # (EmbedderVersionMismatchError) instead of mixing them in.
        enrol_version = getattr(self.pipeline.gallery, "embedder_version",
                                None)
        crops = np.stack(
            [np.asarray(image_ops.resize(c, face_size)) for c in enrolment.crops]  # ocvf-lint: boundary=host-sync -- enrolment readback: _finish_enrolment runs on its own daemon thread, off the serving loop by design
        )
        # Embed in fixed-size padded chunks (pre-compiled in warmup()).
        embeddings = []
        for start in range(0, len(crops), self._enrol_chunk):
            part = crops[start : start + self._enrol_chunk]
            padded = np.zeros((self._enrol_chunk, *face_size), np.float32)
            padded[: len(part)] = part
            emb = np.array(self._run_embed_chunk(self.pipeline.embed_params,  # ocvf-lint: boundary=host-sync -- enrolment embed readback on the dedicated enrolment thread; frame batches keep flowing while this blocks
                                                 padded))
            embeddings.append(emb[: len(part)])
        emb = np.concatenate(embeddings)
        with self._enrol_lock:
            if enrolment.subject_name in self.subject_names:
                label = self.subject_names.index(enrolment.subject_name)
            else:
                label = len(self.subject_names)
                self.subject_names.append(enrolment.subject_name)
        before_grow = self.pipeline.gallery.grow_count
        labels_arr = np.full(len(emb), label, np.int32)
        try:
            if self.state is not None:
                # Write-ahead: the WAL record (fsynced per policy) lands
                # BEFORE the gallery mutation, both under the lifecycle's
                # enroll lock — a crash anywhere after the append replays
                # this enrolment on restart, and the 'enrolled' ack below
                # is a durability promise. A failed append raises: the
                # enrolment is rolled back, never acknowledged-but-lost.
                self.state.append_enrollment(
                    emb, labels_arr, subject=enrolment.subject_name,
                    label=label,
                    apply_fn=lambda: self.pipeline.gallery.add(emb, labels_arr),
                    embedder_version=enrol_version)
            else:
                self.pipeline.gallery.add(emb, labels_arr)  # ocvf-lint: boundary=wal-before-mutate -- explicit no-state-dir mode: nothing durable exists to sequence against, and the operator chose volatility
            grown = self.pipeline.gallery.grow_count - before_grow
            if grown:
                # Auto-grow saved the enrolment but forced a recompile-sized
                # stall on the next match — surface it so operators pre-size.
                self.metrics.incr(mn.GALLERY_GROWN, grown)
        except Exception as exc:
            # Roll back a name we just reserved: the gallery has no rows
            # for it, so leaving it would skew label->name indices.
            with self._enrol_lock:
                if (label == len(self.subject_names) - 1
                        and self.subject_names[label] == enrolment.subject_name):
                    self.subject_names.pop()
            if isinstance(exc, (DurabilityDegradedError, OSError)):
                # Storage-shaped refusal (ISSUE 15): the enrollment was
                # refused closed — never acknowledged, nothing durable
                # burned. Surface the explicit status (counting already
                # happened at the layer that refused: the lifecycle's
                # enrollments_refused_degraded / the WAL's
                # wal_append_errors) instead of killing the enrolment
                # thread with a silent traceback.
                logging.getLogger(__name__).warning(
                    "enrollment %r refused closed: %r",
                    enrolment.subject_name, exc)
                self._publish_status({
                    "status": "enroll_failed",
                    "subject": enrolment.subject_name,
                    "reason": ("durability_degraded"
                               if isinstance(exc, DurabilityDegradedError)
                               else "wal_error"),
                    "error": repr(exc)})
                return
            raise
        self.metrics.incr(mn.SUBJECTS_ENROLLED)
        self.connector.publish(
            STATUS_TOPIC,
            {
                "status": "enrolled",
                "subject": enrolment.subject_name,
                "label": label,
                "gallery_size": self.pipeline.gallery.size,
            },
        )
        self._run_commit_hooks()

    # ---- reload without drop (SURVEY.md §5.3) ----

    def reload_gallery(self, new_gallery) -> None:
        """Swap in a rebuilt gallery between batches (double-buffered)."""
        self.pipeline.gallery.swap_from(new_gallery)
        if self.tracker is not None:
            # Cached identities were verified against the OLD gallery's
            # labels/names: cold-start the cache (the embedder-version
            # fence catches cutovers, but a same-version swap can still
            # renumber labels).
            self.tracker.flush_all()
        self.connector.publish(STATUS_TOPIC, {"status": "reloaded",
                                              "gallery_size": self.pipeline.gallery.size})
        self._run_commit_hooks()
        if self.state is not None:
            # A swap is not WAL-representable (the log speaks in appended
            # rows): force a durable checkpoint of the NEW gallery. Until
            # it lands, a crash recovers the previous gallery plus every
            # acknowledged enrolment — the documented reload window.
            self.state.maybe_checkpoint(force=True)

    def _run_commit_hooks(self) -> None:
        """Notify commit watchers (see ``commit_hooks``); a raising hook
        must not kill the enrolment worker or the reload caller."""
        for hook in list(self.commit_hooks):
            try:
                hook()
            except Exception:  # noqa: BLE001 — watcher bugs stay theirs
                logging.getLogger(__name__).exception("commit hook failed")
