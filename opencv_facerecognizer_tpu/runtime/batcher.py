"""Frame batcher: the host-side stage that turns an async frame stream into
fixed-size device batches (BASELINE.json:5: "buffers incoming sensor_msgs/
Image into fixed-size device batches"; SURVEY.md §5.2 — this queue is the
one real concurrency point, so it is small, locked, and directly tested).

Semantics:
- ``put`` validates shape/dtype and drops malformed frames (SURVEY.md §5.3
  graceful skip) — a camera glitch must not poison a whole batch.
- ``get_batch`` implements **continuous batching**: it blocks until
  ``batch_size`` frames are buffered OR the oldest undelivered frame's age
  reaches the current flush deadline, then returns a zero-padded [B, H, W]
  batch plus the metadata list and real count. The deadline is either the
  fixed ``flush_timeout`` (legacy mode) or, with ``target_latency_s`` set,
  **adaptive**: the remaining per-frame latency budget after subtracting an
  EWMA of the downstream service time the consumer reports via
  ``report_service_time`` — under trickle load a batch waits only as long
  as the end-to-end target can afford, never a fixed window. Fixed B keeps
  XLA from recompiling (static shapes); padding lanes are dead weight the
  TPU shrugs off (partial batches can additionally be *sliced* down to a
  bucket ladder by the consumer — see RecognizerService).
- Bounded queue: beyond ``max_pending`` the OLDEST frames drop first — a
  live recognizer wants fresh frames, not a growing latency debt.
- **Buffer pool**: the [B, H, W] staging array a batch rides in can be
  handed back via ``recycle`` once the consumer is done with it (after the
  batch's readback completed — the host-side analog of a donated input
  buffer). Steady-state batching then does zero per-batch allocations;
  consumers that never recycle just get a fresh array each time, exactly
  the old behavior. A recycled buffer's padding lanes are re-zeroed before
  reuse.

Coalescing stats ride the shared ``Metrics`` surface so tests can reconcile
them exactly: ``batcher_frames_offered`` (every ``put`` attempt) equals
frames batched + malformed drops + overflow drops + closed drops + pending.
``batcher_batches_size`` / ``batcher_batches_deadline`` split batches by
what triggered the flush; ``batcher_flush_deadline_ms`` is a gauge of the
current (possibly adaptive) deadline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np


class Batch(NamedTuple):
    """One device-ready batch plus the provenance the latency decomposition
    needs: ``enqueue_ts`` are the ``time.monotonic()`` stamps from ``put``
    for the ``count`` real frames (queue-wait = pop time - enqueue time)."""

    frames: np.ndarray  # [B, H, W] in the batcher's dtype, zero-padded
    metas: List[Any]
    count: int
    enqueue_ts: List[float]


class FrameBatcher:
    def __init__(
        self,
        batch_size: int,
        frame_shape: Tuple[int, int],
        flush_timeout: float = 0.05,
        max_pending: int = 256,
        dtype=np.float32,
        # Shared Metrics mirror of the drop/coalescing counters (None =
        # stats-only): the chaos/connector/batching tests assert through
        # ONE metrics surface instead of poking per-component attributes.
        metrics=None,
        # Chaos hook (runtime.faults): may poison a frame before the
        # shape/dtype validation that must then drop it.
        fault_injector=None,
        # Continuous-batching target: when set, the flush deadline adapts
        # to ``target_latency_s - EWMA(downstream service time)`` instead
        # of the fixed flush_timeout (which then acts as the CAP). The
        # consumer feeds the EWMA via report_service_time after each
        # batch completes end-to-end.
        target_latency_s: Optional[float] = None,
        # Floor of the adaptive deadline: even with no latency budget left
        # a flush waits this long so back-to-back frames still coalesce.
        min_deadline_s: float = 0.002,
        # EWMA smoothing for the reported service time.
        service_time_alpha: float = 0.2,
        # Staging buffers kept for reuse (recycle); ~inflight_depth + the
        # batch being formed is plenty.
        buffer_pool_size: int = 8,
    ):
        self.batch_size = int(batch_size)
        self.frame_shape = tuple(frame_shape)
        self.flush_timeout = float(flush_timeout)
        self.max_pending = int(max_pending)
        # uint8 halves memory 4x AND rides host->device 4x cheaper (the
        # pipeline casts to f32 in-graph); camera frames are uint8 anyway.
        self.dtype = np.dtype(dtype)
        self.metrics = metrics
        self._faults = fault_injector
        self.target_latency_s = (None if target_latency_s is None
                                 else float(target_latency_s))
        self.min_deadline_s = float(min_deadline_s)
        self._alpha = float(service_time_alpha)
        self._service_time_ewma: Optional[float] = None
        self._pool_cap = int(buffer_pool_size)
        self._buffer_pool: List[np.ndarray] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._frames: deque = deque()
        self._dropped_malformed = 0
        self._dropped_overflow = 0
        self._delivered = 0
        self._batches_size = 0
        self._batches_deadline = 0
        self._closed = False

    # ---- producer side ----

    def put(self, frame: np.ndarray, meta: Any = None) -> bool:
        """Enqueue one frame; returns False when dropped (malformed/closed)."""
        if self.metrics is not None:
            self.metrics.incr("batcher_frames_offered")
        if self._faults is not None:
            frame = self._faults.on_put(frame)
        frame = np.asarray(frame)
        if frame.shape != self.frame_shape or not np.issubdtype(frame.dtype, np.number):
            with self._lock:
                self._dropped_malformed += 1
            if self.metrics is not None:
                self.metrics.incr("batcher_dropped_malformed")
            return False
        with self._not_empty:
            if self._closed:
                if self.metrics is not None:
                    self.metrics.incr("batcher_dropped_closed")
                return False
            if len(self._frames) >= self.max_pending:
                self._frames.popleft()  # drop oldest: freshness over backlog
                self._dropped_overflow += 1
                if self.metrics is not None:
                    self.metrics.incr("batcher_dropped_overflow")
            if np.issubdtype(self.dtype, np.integer) and not np.issubdtype(
                    frame.dtype, np.integer):
                # A bare astype would WRAP out-of-range floats (-3.0 -> 253)
                # — clip to the integer range instead (producers may send
                # slight out-of-[0,255] values from preprocessing headroom).
                info = np.iinfo(self.dtype)
                frame = np.clip(frame, info.min, info.max)
            self._frames.append((frame.astype(self.dtype), meta, time.monotonic()))
            self._not_empty.notify()
        return True

    def close(self) -> None:
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()

    # ---- adaptive deadline (continuous batching) ----

    def report_service_time(self, seconds: float) -> None:
        """Feed one batch's downstream time (pop -> published) into the
        EWMA the adaptive flush deadline subtracts from the latency target.
        Cheap and lock-free on purpose: a float store is atomic in CPython,
        and the deadline only needs a recent estimate, not a serialized
        one."""
        if seconds < 0:
            return
        prev = self._service_time_ewma
        self._service_time_ewma = (seconds if prev is None
                                   else prev + self._alpha * (seconds - prev))

    def current_flush_deadline(self) -> float:
        """Seconds the oldest frame may age before a partial batch flushes.
        Fixed ``flush_timeout`` without a latency target; with one, the
        remaining budget after the estimated downstream service time,
        clamped to [min_deadline_s, flush_timeout]."""
        if self.target_latency_s is None:
            return self.flush_timeout
        est = self._service_time_ewma or 0.0
        deadline = min(self.flush_timeout,
                       max(self.min_deadline_s, self.target_latency_s - est))
        if self.metrics is not None:
            self.metrics.set_gauge("batcher_flush_deadline_ms", deadline * 1e3)
        return deadline

    # ---- buffer pool (host-side donated staging) ----

    def recycle(self, buf: np.ndarray) -> None:
        """Return a batch's staging array for reuse once the consumer is
        completely done with it (readback finished, no views kept — crops
        must be copied out first). Wrong shape/dtype or a full pool just
        drops it; never an error."""
        if (not isinstance(buf, np.ndarray)
                or buf.shape != (self.batch_size, *self.frame_shape)
                or buf.dtype != self.dtype):
            return
        with self._lock:
            if len(self._buffer_pool) < self._pool_cap:
                self._buffer_pool.append(buf)

    # ---- consumer side ----

    def get_batch(self, block: bool = True) -> Optional[Batch]:
        """Next ``Batch`` or None when closed and drained (or when
        non-blocking and nothing is flushable)."""
        with self._not_empty:
            while True:
                n = len(self._frames)
                if n >= self.batch_size:
                    break
                if n > 0:
                    deadline = self.current_flush_deadline()
                    age = time.monotonic() - self._frames[0][2]
                    if age >= deadline:
                        break
                    if not block:
                        return None
                    self._not_empty.wait(timeout=deadline - age)
                    continue
                if self._closed:
                    return None
                if not block:
                    return None
                self._not_empty.wait(timeout=self.flush_timeout)
                if not self._frames:
                    # Idle tick: give the caller a turn (the fallback
                    # serving loop drains its in-flight queue on None).
                    return None
            count = min(len(self._frames), self.batch_size)
            full = count >= self.batch_size
            items = [self._frames.popleft() for _ in range(count)]
            # Counted under the lock, atomically with the pop: consumers
            # (RecognizerService.drain) compare this against their own
            # completion count, so a popped-but-not-yet-dispatched batch is
            # never invisible to both ``pending`` and the in-flight queue.
            self._delivered += 1
            if full:
                self._batches_size += 1
            else:
                self._batches_deadline += 1
            buf = self._buffer_pool.pop() if self._buffer_pool else None
        if self.metrics is not None:
            self.metrics.incr("batcher_batches_size" if full
                              else "batcher_batches_deadline")
            self.metrics.incr("batcher_frames_batched", count)
            if buf is not None:
                self.metrics.incr("batcher_buffer_reuse")
        if buf is None:
            frames = np.zeros((self.batch_size, *self.frame_shape), dtype=self.dtype)
        else:
            frames = buf
            frames[count:] = 0  # re-zero a reused buffer's padding lanes
        metas: List[Any] = [None] * self.batch_size
        enqueue_ts: List[float] = []
        for i, (frame, meta, ts) in enumerate(items):
            frames[i] = frame
            metas[i] = meta
            enqueue_ts.append(ts)
        return Batch(frames, metas, count, enqueue_ts)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._frames)

    @property
    def delivered_batches(self) -> int:
        """Batches handed out by ``get_batch`` (incremented under the lock,
        atomically with the pop)."""
        with self._lock:
            return self._delivered

    @property
    def stats(self):
        with self._lock:
            return {
                "pending": len(self._frames),
                "dropped_malformed": self._dropped_malformed,
                "dropped_overflow": self._dropped_overflow,
                "batches_size": self._batches_size,
                "batches_deadline": self._batches_deadline,
            }
