"""Frame batcher: the host-side stage that turns an async frame stream into
fixed-size device batches (BASELINE.json:5: "buffers incoming sensor_msgs/
Image into fixed-size device batches"; SURVEY.md §5.2 — this queue is the
one real concurrency point, so it is small, locked, and directly tested).

Semantics:
- ``put`` validates shape/dtype and drops malformed frames (SURVEY.md §5.3
  graceful skip) — a camera glitch must not poison a whole batch.
- ``get_batch`` implements **continuous batching**: it blocks until
  ``batch_size`` frames are buffered OR the oldest undelivered frame's age
  reaches the current flush deadline, then returns a zero-padded [B, H, W]
  batch plus the metadata list and real count. The deadline is either the
  fixed ``flush_timeout`` (legacy mode) or, with ``target_latency_s`` set,
  **adaptive**: the remaining per-frame latency budget after subtracting an
  EWMA of the downstream service time the consumer reports via
  ``report_service_time`` — under trickle load a batch waits only as long
  as the end-to-end target can afford, never a fixed window. Fixed B keeps
  XLA from recompiling (static shapes); padding lanes are dead weight the
  TPU shrugs off (partial batches can additionally be *sliced* down to a
  bucket ladder by the consumer — see RecognizerService).
- Bounded queue with **priority-aware shedding**: beyond ``max_pending`` a
  victim is evicted in preference order — already-stale frames first (queue
  age past ``stale_after_s``), then the lowest-priority class (bulk before
  interactive), oldest within a class. An incoming frame less important
  than everything queued is itself the victim (rejected). Without
  priorities or a stale bound this degrades exactly to the old
  drop-oldest-first rule: a live recognizer wants fresh frames, not a
  growing latency debt.
- **Deadline-aware dispatch**: with ``stale_after_s`` set, ``get_batch``
  discards frames whose queue age already exceeds it BEFORE forming a
  batch — a frame that has blown its latency budget must not waste a
  dispatch slot that a fresh frame could use (``batcher_dropped_stale``).
- Every drop is observable twice: per-reason counters on the shared
  Metrics surface, and (when ``drop_log`` is wired) the dropped frames'
  metadata handed to the service's dead-letter journal.
- **Buffer pool**: the [B, H, W] staging array a batch rides in can be
  handed back via ``recycle`` once the consumer is done with it (after the
  batch's readback completed — the host-side analog of a donated input
  buffer). Steady-state batching then does zero per-batch allocations;
  consumers that never recycle just get a fresh array each time, exactly
  the old behavior. A recycled buffer's padding lanes are re-zeroed before
  reuse.

Coalescing stats ride the shared ``Metrics`` surface so tests can reconcile
them exactly: ``batcher_frames_offered`` (every ``put`` attempt) equals
frames batched + malformed drops + overflow drops + stale drops + closed
drops + pending.
``batcher_batches_size`` / ``batcher_batches_deadline`` split batches by
what triggered the flush; ``batcher_flush_deadline_ms`` is a gauge of the
current (possibly adaptive) deadline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np
from opencv_facerecognizer_tpu.utils import metric_names as mn

#: in-loop marker that a staging-ring acquire already missed this pop
#: attempt — later re-checks of the same episode go ``quiet`` so the
#: exhaustion counter stays per-episode (see StagingRing.acquire).
_EXHAUSTED = object()


class Batch(NamedTuple):
    """One device-ready batch plus the provenance the latency decomposition
    needs: ``enqueue_ts`` are the ``time.monotonic()`` stamps from ``put``
    for the ``count`` real frames (queue-wait = pop time - enqueue time);
    ``trace_ids`` are their frame-trace ids (0 = untraced/sampled out) so
    the consumer can record which batch carried each frame; ``priorities``
    are their admission priority classes (the SLO layer's per-class e2e
    histograms split on them at publish time)."""

    frames: np.ndarray  # [B, H, W] in the batcher's dtype, zero-padded
    metas: List[Any]
    count: int
    enqueue_ts: List[float]
    trace_ids: List[int]
    priorities: List[int]


class FrameBatcher:
    def __init__(
        self,
        batch_size: int,
        frame_shape: Tuple[int, int],
        flush_timeout: float = 0.05,
        max_pending: int = 256,
        dtype=np.float32,
        # Shared Metrics mirror of the drop/coalescing counters (None =
        # stats-only): the chaos/connector/batching tests assert through
        # ONE metrics surface instead of poking per-component attributes.
        metrics=None,
        # Chaos hook (runtime.faults): may poison a frame before the
        # shape/dtype validation that must then drop it.
        fault_injector=None,
        # Continuous-batching target: when set, the flush deadline adapts
        # to ``target_latency_s - EWMA(downstream service time)`` instead
        # of the fixed flush_timeout (which then acts as the CAP). The
        # consumer feeds the EWMA via report_service_time after each
        # batch completes end-to-end.
        target_latency_s: Optional[float] = None,
        # Floor of the adaptive deadline: even with no latency budget left
        # a flush waits this long so back-to-back frames still coalesce.
        min_deadline_s: float = 0.002,
        # EWMA smoothing for the reported service time.
        service_time_alpha: float = 0.2,
        # Staging buffers kept for reuse (recycle); ~inflight_depth + the
        # batch being formed is plenty.
        buffer_pool_size: int = 8,
        # Ingest staging ring (runtime.ingest.StagingRing): when set, it
        # REPLACES the ad-hoc buffer pool — batches assemble into
        # pre-allocated per-rung buffers, recycle/forfeit route to the
        # ring, and an exhausted ring makes the consumer WAIT (explicit
        # backpressure) instead of allocating. Must match this batcher's
        # frame_shape/dtype, and its largest rung must be batch_size.
        staging_ring=None,
        # Freshness bound (seconds): a queued frame older than this is shed
        # (reason ``stale``) — preferentially at overflow-eviction time, and
        # always before it can consume a dispatch slot. None disables.
        stale_after_s: Optional[float] = None,
        # Drop observer: called OUTSIDE the lock as ``drop_log(reason,
        # entries)`` with entries = [{"meta", "enqueue_ts", "priority",
        # "trace_id", "stage"}] for overflow/stale sheds (the service
        # wires its dead-letter journal here). None = counters only.
        drop_log=None,
        # Frame-lifecycle tracer (utils.tracing.Tracer): every drop the
        # batcher counts also emits the frame's terminal ``settle`` span
        # (outcome = the ledger counter it landed in), outside the queue
        # lock. ``trace_topic`` is the ring topic frame spans ride on
        # (the service passes its FRAME_TOPIC). None = no spans.
        tracer=None,
        trace_topic: Optional[str] = None,
    ):
        self.batch_size = int(batch_size)
        self.frame_shape = tuple(frame_shape)
        self.flush_timeout = float(flush_timeout)
        self.max_pending = int(max_pending)
        # uint8 halves memory 4x AND rides host->device 4x cheaper (the
        # pipeline casts to f32 in-graph); camera frames are uint8 anyway.
        self.dtype = np.dtype(dtype)
        self.metrics = metrics
        self._faults = fault_injector
        self.target_latency_s = (None if target_latency_s is None
                                 else float(target_latency_s))
        self.min_deadline_s = float(min_deadline_s)
        self._alpha = float(service_time_alpha)
        self._service_time_ewma: Optional[float] = None
        self._pool_cap = int(buffer_pool_size)
        self._buffer_pool: List[np.ndarray] = []
        self._ring = staging_ring
        if self._ring is not None:
            if (tuple(self._ring.frame_shape) != self.frame_shape
                    or np.dtype(self._ring.dtype) != self.dtype):
                raise ValueError(
                    "staging_ring shape/dtype "
                    f"({self._ring.frame_shape}, {self._ring.dtype}) does "
                    f"not match batcher ({self.frame_shape}, {self.dtype})")
            if max(self._ring.rungs) < self.batch_size:
                raise ValueError(
                    f"staging_ring's largest rung {max(self._ring.rungs)} "
                    f"cannot stage a full batch of {self.batch_size}")
            # Wake a consumer parked on ring exhaustion when a buffer
            # returns (called by the ring OUTSIDE its own lock).
            self._ring.add_notify(self._wake_consumer)
        self.stale_after_s = (None if stale_after_s is None
                              else float(stale_after_s))
        self._drop_log = drop_log
        self._tracer = tracer
        self._trace_topic = trace_topic
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._frames: deque = deque()
        self._dropped_malformed = 0
        self._dropped_overflow = 0
        self._dropped_stale = 0
        self._delivered = 0
        self._batches_size = 0
        self._batches_deadline = 0
        self._closed = False

    # ---- producer side ----

    def put(self, frame: np.ndarray, meta: Any = None, priority: int = 0,
            trace_id: int = 0) -> bool:
        """Enqueue one frame (smaller ``priority`` = more important);
        returns False when dropped (malformed/closed/rejected-at-overflow).
        ``trace_id`` is the frame's trace (0 = untraced); every drop path
        emits its terminal span so traced frames never vanish silently."""
        if self.metrics is not None:
            self.metrics.incr(mn.BATCHER_FRAMES_OFFERED)
        if self._faults is not None:
            frame = self._faults.on_put(frame)
        frame = np.asarray(frame)
        if frame.shape != self.frame_shape or not np.issubdtype(frame.dtype, np.number):
            with self._lock:
                self._dropped_malformed += 1
            if self.metrics is not None:
                self.metrics.incr(mn.BATCHER_DROPPED_MALFORMED)
            self._emit_settle(trace_id, mn.BATCHER_DROPPED_MALFORMED,
                              "batcher.malformed")
            return False
        dropped = None  # (reason, entry) settled outside the lock
        accepted = True
        closed = False
        with self._not_empty:
            if self._closed:
                # Counted under the lock (the one sanctioned
                # FrameBatcher._lock -> Metrics._lock nesting, cross-checked
                # by the DebugLock backstop); the span emits outside below.
                closed = True
                if self.metrics is not None:
                    self.metrics.incr(mn.BATCHER_DROPPED_CLOSED)
            elif len(self._frames) >= self.max_pending:
                dropped = self._evict_for(int(priority))
                accepted = dropped is not None
            if not closed and accepted:
                if np.issubdtype(self.dtype, np.integer) and not np.issubdtype(
                        frame.dtype, np.integer):
                    # A bare astype would WRAP out-of-range floats (-3.0 ->
                    # 253) — clip to the integer range instead (producers may
                    # send slight out-of-[0,255] values from preprocessing
                    # headroom).
                    info = np.iinfo(self.dtype)
                    frame = np.clip(frame, info.min, info.max)
                self._frames.append((frame.astype(self.dtype), meta,
                                     time.monotonic(), int(priority),
                                     int(trace_id)))
                self._not_empty.notify()
        if closed:
            self._emit_settle(trace_id, mn.BATCHER_DROPPED_CLOSED,
                              "batcher.closed")
            return False
        if not accepted:
            # The incoming frame was the least important thing in sight:
            # IT is the overflow victim, not a queued frame.
            with self._lock:
                self._dropped_overflow += 1
            if self.metrics is not None:
                self.metrics.incr(mn.BATCHER_DROPPED_OVERFLOW)
            self._emit_settle(trace_id, mn.BATCHER_DROPPED_OVERFLOW,
                              "batcher.overflow")
            self._log_drop("overflow", [(meta, None, int(priority),
                                         int(trace_id))])
            return False
        if dropped is not None:
            reason, entry = dropped
            if self.metrics is not None:
                self.metrics.incr(mn.BATCHER_DROPPED_PREFIX + reason)
            self._emit_settle(entry[3], mn.BATCHER_DROPPED_PREFIX + reason,
                              f"batcher.{reason}")
            self._log_drop(reason, [entry])
        return True

    def _evict_for(self, incoming_priority: int):
        """Caller holds the lock; the queue is full. Pick and remove the
        overflow victim: the oldest already-stale frame if any, else the
        oldest frame of the least-important queued class — but only when
        that class is at least as unimportant as the incoming frame.
        Returns ``(reason, (meta, enqueue_ts, priority, trace_id))`` for
        the evicted frame, or None when the INCOMING frame should be
        rejected instead (everything queued outranks it)."""
        if self.stale_after_s is not None and self._frames:
            # Only the head can be stale: enqueue stamps are nondecreasing,
            # so staleness is a deque prefix (same fact _shed_stale uses) —
            # no O(max_pending) scan on the per-put overflow path.
            _f, meta, ts, pri, tid = self._frames[0]
            if time.monotonic() - ts > self.stale_after_s:
                self._frames.popleft()
                self._dropped_stale += 1
                return "stale", (meta, ts, pri, tid)
        victim_idx, victim_pri = None, -1
        for idx, (_f, _meta, _ts, pri, _tid) in enumerate(self._frames):
            if pri > victim_pri:  # strictly-greater keeps the OLDEST of a class
                victim_idx, victim_pri = idx, pri
        if victim_pri < incoming_priority:
            return None  # incoming is the least important: reject it
        _f, meta, ts, pri, tid = self._frames[victim_idx]
        del self._frames[victim_idx]
        self._dropped_overflow += 1
        return "overflow", (meta, ts, pri, tid)

    def _emit_settle(self, trace_id: int, outcome: str, where: str) -> None:
        """Terminal span for a frame the batcher dropped (no-op untraced).
        Always called OUTSIDE the queue lock — span emission is lock-free
        but must never nest inside serving-path locks anyway."""
        if self._tracer is not None and trace_id:
            self._tracer.emit(trace_id, "settle", topic=self._trace_topic,
                              outcome=outcome, where=where)

    def _log_drop(self, reason: str, items) -> None:
        """Hand dropped frames' metadata to the drop observer (journal).
        Called OUTSIDE the queue lock; a raising observer is its own bug
        and must not poison the producer thread. Entries carry the frame's
        ``trace_id`` and the ``stage`` it died at, so a journal replay can
        reconstruct where each dropped frame died."""
        if self._drop_log is None:
            return
        entries = [{"meta": meta, "enqueue_ts": ts, "priority": pri,
                    "trace_id": tid or None, "stage": f"batcher.{reason}"}
                   for meta, ts, pri, tid in items]
        try:
            self._drop_log(reason, entries)
        except Exception:  # noqa: BLE001 — observer bugs stay theirs, but a
            # lost journal write must leave a trace: the soak's "journal
            # covers every shed frame" check needs to know entries went
            # missing (ocvf-lint swallowed-exception).
            if self.metrics is not None:
                self.metrics.incr(mn.JOURNAL_ERRORS)

    def close(self) -> None:
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()

    # ---- adaptive deadline (continuous batching) ----

    def report_service_time(self, seconds: float) -> None:
        """Feed one batch's downstream time (pop -> published) into the
        EWMA the adaptive flush deadline subtracts from the latency target.
        Cheap and lock-free on purpose: a float store is atomic in CPython,
        and the deadline only needs a recent estimate, not a serialized
        one."""
        if seconds < 0:
            return
        prev = self._service_time_ewma
        self._service_time_ewma = (seconds if prev is None
                                   else prev + self._alpha * (seconds - prev))

    def current_flush_deadline(self) -> float:
        """Seconds the oldest frame may age before a partial batch flushes.
        Fixed ``flush_timeout`` without a latency target; with one, the
        remaining budget after the estimated downstream service time,
        clamped to [min_deadline_s, flush_timeout]."""
        if self.target_latency_s is None:
            return self.flush_timeout
        est = self._service_time_ewma or 0.0
        deadline = min(self.flush_timeout,
                       max(self.min_deadline_s, self.target_latency_s - est))
        if self.metrics is not None:
            self.metrics.set_gauge(mn.BATCHER_FLUSH_DEADLINE_MS, deadline * 1e3)
        return deadline

    # ---- buffer pool (host-side donated staging) ----

    def recycle(self, buf: np.ndarray) -> None:
        """Return a batch's staging array for reuse once the consumer is
        completely done with it (readback finished, no views kept — crops
        must be copied out first). Wrong shape/dtype or a full pool just
        drops it; never an error. With a staging ring installed the buffer
        goes back to its rung's pre-allocated pool instead."""
        if self._ring is not None:
            self._ring.release(buf)
            return
        if (not isinstance(buf, np.ndarray)
                or buf.shape != (self.batch_size, *self.frame_shape)
                or buf.dtype != self.dtype):
            return
        with self._lock:
            if len(self._buffer_pool) < self._pool_cap:
                self._buffer_pool.append(buf)

    def forfeit(self, buf) -> None:
        """Tell the staging ring one in-flight buffer will never come back
        (dead-letter/crash paths: the backend's async H2D read of it may
        still be pending, so it must not recirculate). No-op without a
        ring — the legacy pool refills from completed batches anyway."""
        if self._ring is not None:
            self._ring.forfeit(buf)

    def _wake_consumer(self) -> None:
        """Ring release notification: a consumer parked on ring
        exhaustion inside ``get_batch`` re-checks for a free buffer."""
        with self._not_empty:
            self._not_empty.notify_all()

    # ---- consumer side ----

    def get_batch(self, block: bool = True) -> Optional[Batch]:
        """Next ``Batch`` or None when closed and drained (or when
        non-blocking and nothing is flushable). With ``stale_after_s``
        set, frames that outlived their freshness bound while queued are
        shed here — counted, journaled, and never dispatched."""
        stale: List[tuple] = []
        try:
            with self._not_empty:
                popped = self._pop_batch_locked(block, stale)
        finally:
            if stale:
                if self.metrics is not None:
                    self.metrics.incr(mn.BATCHER_DROPPED_STALE, len(stale))
                for _meta, _ts, _pri, tid in stale:
                    self._emit_settle(tid, mn.BATCHER_DROPPED_STALE,
                                      "batcher.stale")
                self._log_drop("stale", stale)
        if popped is None:
            return None
        items, count, full, buf = popped
        if self.metrics is not None:
            self.metrics.incr(mn.BATCHER_BATCHES_SIZE if full
                              else mn.BATCHER_BATCHES_DEADLINE)
            self.metrics.incr(mn.BATCHER_FRAMES_BATCHED, count)
            if buf is not None:
                self.metrics.incr(mn.BATCHER_BUFFER_REUSE)
        if buf is None:
            frames = np.zeros((self.batch_size, *self.frame_shape), dtype=self.dtype)
        else:
            # A ring buffer may be RUNG-sized (the smallest dispatch
            # bucket >= count) rather than batch_size — the consumer's
            # bucket slicing handles either length.
            frames = buf
            frames[count:] = 0  # re-zero a reused buffer's padding lanes
        metas: List[Any] = [None] * self.batch_size
        enqueue_ts: List[float] = []
        trace_ids: List[int] = []
        priorities: List[int] = []
        for i, (frame, meta, ts, pri, tid) in enumerate(items):
            frames[i] = frame
            metas[i] = meta
            enqueue_ts.append(ts)
            trace_ids.append(tid)
            priorities.append(pri)
        return Batch(frames, metas, count, enqueue_ts, trace_ids, priorities)

    def _shed_stale(self, collector: List[tuple]) -> None:
        """Caller holds the lock. Frames are FIFO by enqueue time, so
        staleness is always a prefix of the deque."""
        if self.stale_after_s is None:
            return
        now = time.monotonic()
        while self._frames and now - self._frames[0][2] > self.stale_after_s:
            _frame, meta, ts, pri, tid = self._frames.popleft()
            self._dropped_stale += 1
            collector.append((meta, ts, pri, tid))

    def _pop_batch_locked(self, block: bool, stale: List[tuple]):
        """Caller holds the lock: the wait/flush decision + the pop.
        Returns ``(items, count, full, pooled_buf)`` or None (closed /
        nothing flushable / idle tick). With a staging ring, the buffer
        is acquired BEFORE the pop — an exhausted ring keeps the frames
        queued (backpressure: admission sheds new intake upstream) and
        waits for a recycled buffer instead of ever allocating."""
        buf = None
        while True:
            self._shed_stale(stale)
            n = len(self._frames)
            if n >= self.batch_size:
                pass  # full batch: flush now
            elif n > 0:
                deadline = self.current_flush_deadline()
                age = time.monotonic() - self._frames[0][2]
                if age < deadline:
                    if not block:
                        return None
                    self._not_empty.wait(timeout=deadline - age)
                    continue
            else:
                if self._closed or not block:
                    return None
                self._not_empty.wait(timeout=self.flush_timeout)
                if not self._frames:
                    # Idle tick: give the caller a turn (the fallback
                    # serving loop drains its in-flight queue on None).
                    return None
                continue
            count = min(len(self._frames), self.batch_size)
            if self._ring is None:
                break
            # The one sanctioned FrameBatcher._lock -> StagingRing._lock
            # nesting; the ring never calls back under its own lock.
            # ``quiet`` after the first miss: one exhaustion EPISODE
            # counts once, not once per 10 ms re-check below.
            buf = self._ring.acquire(count, quiet=buf is _EXHAUSTED)
            if buf is not None:
                break
            buf = _EXHAUSTED
            if self._closed or not block:
                # Shutdown with an exhausted ring: surrender the tick
                # (same as legacy stop semantics — queued frames are the
                # drain/stop caller's problem, never an allocation here).
                return None
            # Exhausted: park until recycle()/release wakes us (the ring
            # notifies this cv) or the timeout re-checks; the queued
            # frames age meanwhile, which is exactly the backpressure
            # signal admission + stale shedding act on.
            self._not_empty.wait(timeout=min(self.flush_timeout, 0.01))
        count = min(len(self._frames), self.batch_size)
        full = count >= self.batch_size
        items = [self._frames.popleft() for _ in range(count)]
        # Counted under the lock, atomically with the pop: consumers
        # (RecognizerService.drain) compare this against their own
        # completion count, so a popped-but-not-yet-dispatched batch is
        # never invisible to both ``pending`` and the in-flight queue.
        self._delivered += 1
        if full:
            self._batches_size += 1
        else:
            self._batches_deadline += 1
        if self._ring is None:
            buf = self._buffer_pool.pop() if self._buffer_pool else None
        return items, count, full, buf

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._frames)

    @property
    def delivered_batches(self) -> int:
        """Batches handed out by ``get_batch`` (incremented under the lock,
        atomically with the pop)."""
        with self._lock:
            return self._delivered

    @property
    def stats(self):
        with self._lock:
            return {
                "pending": len(self._frames),
                "dropped_malformed": self._dropped_malformed,
                "dropped_overflow": self._dropped_overflow,
                "dropped_stale": self._dropped_stale,
                "batches_size": self._batches_size,
                "batches_deadline": self._batches_deadline,
            }
