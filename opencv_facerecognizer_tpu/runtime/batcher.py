"""Frame batcher: the host-side stage that turns an async frame stream into
fixed-size device batches (BASELINE.json:5: "buffers incoming sensor_msgs/
Image into fixed-size device batches"; SURVEY.md §5.2 — this queue is the
one real concurrency point, so it is small, locked, and directly tested).

Semantics:
- ``put`` validates shape/dtype and drops malformed frames (SURVEY.md §5.3
  graceful skip) — a camera glitch must not poison a whole batch.
- ``get_batch`` blocks until ``batch_size`` frames are buffered OR
  ``flush_timeout`` has elapsed since the oldest undelivered frame, then
  returns a zero-padded [B, H, W] batch plus the metadata list and real
  count. Fixed B keeps XLA from recompiling (static shapes); padding lanes
  are dead weight the TPU shrugs off.
- Bounded queue: beyond ``max_pending`` the OLDEST frames drop first — a
  live recognizer wants fresh frames, not a growing latency debt.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np


class Batch(NamedTuple):
    """One device-ready batch plus the provenance the latency decomposition
    needs: ``enqueue_ts`` are the ``time.monotonic()`` stamps from ``put``
    for the ``count`` real frames (queue-wait = pop time - enqueue time)."""

    frames: np.ndarray  # [B, H, W] in the batcher's dtype, zero-padded
    metas: List[Any]
    count: int
    enqueue_ts: List[float]


class FrameBatcher:
    def __init__(
        self,
        batch_size: int,
        frame_shape: Tuple[int, int],
        flush_timeout: float = 0.05,
        max_pending: int = 256,
        dtype=np.float32,
        # Shared Metrics mirror of the drop counters (None = stats-only):
        # the chaos/connector tests assert drops through ONE metrics
        # surface instead of poking per-component attributes.
        metrics=None,
        # Chaos hook (runtime.faults): may poison a frame before the
        # shape/dtype validation that must then drop it.
        fault_injector=None,
    ):
        self.batch_size = int(batch_size)
        self.frame_shape = tuple(frame_shape)
        self.flush_timeout = float(flush_timeout)
        self.max_pending = int(max_pending)
        # uint8 halves memory 4x AND rides host->device 4x cheaper (the
        # pipeline casts to f32 in-graph); camera frames are uint8 anyway.
        self.dtype = np.dtype(dtype)
        self.metrics = metrics
        self._faults = fault_injector
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._frames: deque = deque()
        self._dropped_malformed = 0
        self._dropped_overflow = 0
        self._delivered = 0
        self._closed = False

    # ---- producer side ----

    def put(self, frame: np.ndarray, meta: Any = None) -> bool:
        """Enqueue one frame; returns False when dropped (malformed/closed)."""
        if self._faults is not None:
            frame = self._faults.on_put(frame)
        frame = np.asarray(frame)
        if frame.shape != self.frame_shape or not np.issubdtype(frame.dtype, np.number):
            with self._lock:
                self._dropped_malformed += 1
            if self.metrics is not None:
                self.metrics.incr("batcher_dropped_malformed")
            return False
        with self._not_empty:
            if self._closed:
                return False
            if len(self._frames) >= self.max_pending:
                self._frames.popleft()  # drop oldest: freshness over backlog
                self._dropped_overflow += 1
                if self.metrics is not None:
                    self.metrics.incr("batcher_dropped_overflow")
            if np.issubdtype(self.dtype, np.integer) and not np.issubdtype(
                    frame.dtype, np.integer):
                # A bare astype would WRAP out-of-range floats (-3.0 -> 253)
                # — clip to the integer range instead (producers may send
                # slight out-of-[0,255] values from preprocessing headroom).
                info = np.iinfo(self.dtype)
                frame = np.clip(frame, info.min, info.max)
            self._frames.append((frame.astype(self.dtype), meta, time.monotonic()))
            self._not_empty.notify()
        return True

    def close(self) -> None:
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()

    # ---- consumer side ----

    def get_batch(self, block: bool = True) -> Optional[Batch]:
        """Next ``Batch`` or None when closed and drained (or when
        non-blocking and nothing is flushable)."""
        with self._not_empty:
            while True:
                n = len(self._frames)
                if n >= self.batch_size:
                    break
                if n > 0:
                    age = time.monotonic() - self._frames[0][2]
                    if age >= self.flush_timeout:
                        break
                    if not block:
                        return None
                    self._not_empty.wait(timeout=self.flush_timeout - age)
                    continue
                if self._closed:
                    return None
                if not block:
                    return None
                self._not_empty.wait(timeout=self.flush_timeout)
                if not self._frames:
                    # Idle tick: give the caller a turn (the serving loop
                    # drains its in-flight readback queue on None).
                    return None
            count = min(len(self._frames), self.batch_size)
            items = [self._frames.popleft() for _ in range(count)]
            # Counted under the lock, atomically with the pop: consumers
            # (RecognizerService.drain) compare this against their own
            # completion count, so a popped-but-not-yet-dispatched batch is
            # never invisible to both ``pending`` and the in-flight queue.
            self._delivered += 1
        frames = np.zeros((self.batch_size, *self.frame_shape), dtype=self.dtype)
        metas: List[Any] = [None] * self.batch_size
        enqueue_ts: List[float] = []
        for i, (frame, meta, ts) in enumerate(items):
            frames[i] = frame
            metas[i] = meta
            enqueue_ts.append(ts)
        return Batch(frames, metas, count, enqueue_ts)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._frames)

    @property
    def delivered_batches(self) -> int:
        """Batches handed out by ``get_batch`` (incremented under the lock,
        atomically with the pop)."""
        with self._lock:
            return self._delivered

    @property
    def stats(self):
        with self._lock:
            return {
                "pending": len(self._frames),
                "dropped_malformed": self._dropped_malformed,
                "dropped_overflow": self._dropped_overflow,
            }
