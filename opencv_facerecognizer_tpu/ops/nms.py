"""On-device non-maximum suppression with static shapes (SURVEY.md §7.6).

XLA needs static shapes, so NMS is expressed as a fixed-size mask update:
``nms_mask`` takes exactly K candidate boxes (padded upstream) and returns a
boolean keep-mask — no dynamic output sizes anywhere, so the whole detector
decode stays inside one jitted graph and batches under vmap.

Boxes are [y0, x0, y1, x1] in any consistent unit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def box_area(boxes: jnp.ndarray) -> jnp.ndarray:
    h = jnp.maximum(boxes[..., 2] - boxes[..., 0], 0.0)
    w = jnp.maximum(boxes[..., 3] - boxes[..., 1], 0.0)
    return h * w


def pairwise_iou(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[K, 4], [M, 4] -> [K, M] IoU."""
    y0 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    x0 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    y1 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    x1 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.maximum(y1 - y0, 0.0) * jnp.maximum(x1 - x0, 0.0)
    union = box_area(a)[:, None] + box_area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


def nms_mask(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float = 0.45,
    score_threshold: float = 0.0,
) -> jnp.ndarray:
    """Greedy NMS as a fixed-K boolean mask (True = kept).

    Candidates are visited in descending score order; a box is kept iff no
    already-kept, higher-scored box overlaps it above ``iou_threshold``.
    O(K^2) IoU + a K-step ``fori_loop`` — fine for the K<=128 detector
    budget, and fully jittable/vmappable.
    """
    k = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_sorted = jnp.take(boxes, order, axis=0)
    scores_sorted = jnp.take(scores, order)
    iou = pairwise_iou(boxes_sorted, boxes_sorted)
    candidate = scores_sorted > score_threshold
    idx = jnp.arange(k)

    def body(i, keep):
        overlapped = keep & (idx < i) & (iou[i] > iou_threshold)
        return keep.at[i].set(candidate[i] & ~jnp.any(overlapped))

    keep_sorted = jax.lax.fori_loop(0, k, body, candidate)
    # Scatter back to original candidate order.
    keep = jnp.zeros((k,), dtype=bool).at[order].set(keep_sorted)
    return keep


def nms_fixed(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    max_outputs: int,
    iou_threshold: float = 0.45,
    score_threshold: float = 0.0,
):
    """NMS returning exactly ``max_outputs`` (boxes, scores, valid-mask),
    best first; unused slots are zero boxes with -inf score."""
    keep = nms_mask(boxes, scores, iou_threshold, score_threshold)
    masked_scores = jnp.where(keep, scores, -jnp.inf)
    top_scores, top_idx = jax.lax.top_k(masked_scores, max_outputs)
    top_boxes = jnp.take(boxes, top_idx, axis=0)
    valid = jnp.isfinite(top_scores)
    return (
        jnp.where(valid[:, None], top_boxes, 0.0),
        jnp.where(valid, top_scores, -jnp.inf),
        valid,
    )
