"""Distance metrics, batched pairwise by construction.

Rebuilds the capability of the reference's ``facerec/distance.py``
(SURVEY.md §2.1 "Distance metrics": AbstractDistance + Euclidean, Cosine,
NormalizedCorrelation, ChiSquare, HistogramIntersection, BinRatio,
L1BinRatio, ChiSquareBRD), redesigned TPU-first:

- The unit of work is a *pairwise block* ``(Q queries, G gallery) -> [Q, G]``,
  not a scalar pair. Euclidean / cosine / correlation are expressed as one
  matmul plus elementwise terms so XLA tiles them onto the MXU; the
  histogram-family distances are broadcast elementwise reductions fused by
  XLA on the VPU.
- Everything is a pure function of arrays; the thin ``AbstractDistance``
  classes below only carry the name + pairwise fn so the classifier layer
  keeps the reference's pluggable-distance boundary (SURVEY.md §1 L3).

Convention (matches the reference's NearestNeighbor contract): smaller value
== more similar. Similarity measures (cosine, normalized correlation,
histogram intersection) are therefore negated/complemented, which reorders
nothing for k-NN but keeps a single "min is best" rule end-to-end.

The bin-ratio family follows the published Bin Ratio Dissimilarity
definitions (Xie/Hu et al.); the reference mount was empty so exact upstream
formulas could not be re-verified (SURVEY.md §0) — these are capability
rebuilds, not byte-parity ports.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

_EPS = 1e-12

PairwiseFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _mm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full-precision matmul: these distances run on small subspace/LBPH
    features where f32 accuracy beats MXU bf16 throughput (the CNN-embedding
    gallery matcher makes the opposite trade explicitly)."""
    return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)


def _as_2d(x: jnp.ndarray) -> jnp.ndarray:
    """Flatten anything to [batch, dim]; promote a single vector to [1, dim]."""
    x = jnp.asarray(x)
    if x.ndim == 1:
        return x[None, :]
    return x.reshape((x.shape[0], -1))


def euclidean(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Pairwise L2 distance [Q, G] via the ||p||^2 + ||q||^2 - 2 p.q matmul trick."""
    p, q = _as_2d(p), _as_2d(q)
    p2 = jnp.sum(p * p, axis=-1)[:, None]
    q2 = jnp.sum(q * q, axis=-1)[None, :]
    sq = p2 + q2 - 2.0 * _mm(p, q.T)
    return jnp.sqrt(jnp.maximum(sq, 0.0))


def squared_euclidean(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    p, q = _as_2d(p), _as_2d(q)
    p2 = jnp.sum(p * p, axis=-1)[:, None]
    q2 = jnp.sum(q * q, axis=-1)[None, :]
    return jnp.maximum(p2 + q2 - 2.0 * _mm(p, q.T), 0.0)


def cosine(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Negative cosine similarity (min == most similar), one matmul."""
    p, q = _as_2d(p), _as_2d(q)
    pn = p / jnp.maximum(jnp.linalg.norm(p, axis=-1, keepdims=True), _EPS)
    qn = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), _EPS)
    return -_mm(pn, qn.T)


def normalized_correlation(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """1 - Pearson correlation: mean-center each vector, then cosine."""
    p, q = _as_2d(p), _as_2d(q)
    pc = p - jnp.mean(p, axis=-1, keepdims=True)
    qc = q - jnp.mean(q, axis=-1, keepdims=True)
    pn = pc / jnp.maximum(jnp.linalg.norm(pc, axis=-1, keepdims=True), _EPS)
    qn = qc / jnp.maximum(jnp.linalg.norm(qc, axis=-1, keepdims=True), _EPS)
    return 1.0 - _mm(pn, qn.T)


def _broadcast_pair(p: jnp.ndarray, q: jnp.ndarray):
    """[Q, 1, D], [1, G, D] views for elementwise pairwise reductions."""
    p, q = _as_2d(p), _as_2d(q)
    return p[:, None, :], q[None, :, :]


def chi_square(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Chi-square histogram distance: sum (p-q)^2 / (p+q)."""
    pb, qb = _broadcast_pair(p, q)
    d = pb - qb
    s = pb + qb
    return jnp.sum(d * d / jnp.maximum(s, _EPS), axis=-1)


def histogram_intersection(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Negated histogram intersection sum(min(p, q)) so that min == best."""
    pb, qb = _broadcast_pair(p, q)
    return -jnp.sum(jnp.minimum(pb, qb), axis=-1)


def _brd_numerator(p: jnp.ndarray, q: jnp.ndarray):
    """Shared bin-ratio pieces: per-pair cross factor a = |1 - <p,q>| (one
    matmul) and the per-bin numerator (p-q)^2 + 2a*p*q, following the
    upstream facerec-lineage BinRatioDistance definition — the cross term
    couples every bin to the whole-vector dot product, which the plain
    (p-q)^2/(p+q)^2 form drops (ADVICE round 1).

    DOMAIN CAVEAT (applies upstream too): the formula assumes histograms
    normalized to sum 1, where <p,q> <= 1 and a shrinks as vectors align.
    On descriptors whose rows sum to S > 1 — e.g. SpatialHistogram output,
    which L1-normalizes per grid cell so the concatenation sums to the cell
    count — <p,q> can exceed 1 and a GROWS with correlation, which can
    invert nearest-neighbor rankings. Rescale such features by 1/S (or use
    chi_square) before trusting the BRD family."""
    p2, q2 = _as_2d(p), _as_2d(q)
    a = jnp.abs(1.0 - _mm(p2, q2.T))[:, :, None]  # [Q, G, 1]
    pb, qb = _broadcast_pair(p, q)
    d = pb - qb
    num = d * d + 2.0 * a * pb * qb
    return num, d, pb + qb


def bin_ratio(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Bin Ratio Dissimilarity: sum ((p-q)^2 + 2|1-p.q| p q) / (p+q)^2."""
    num, _, s = _brd_numerator(p, q)
    s = jnp.maximum(s, _EPS)
    return jnp.abs(jnp.sum(num / (s * s), axis=-1))


def l1_bin_ratio(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """L1-weighted BRD: sum |p-q| ((p-q)^2 + 2|1-p.q| p q) / (p+q)^2."""
    num, d, s = _brd_numerator(p, q)
    s = jnp.maximum(s, _EPS)
    return jnp.abs(jnp.sum(jnp.abs(d) * num / (s * s), axis=-1))


def chi_square_bin_ratio(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Chi-square-weighted BRD: sum ((p-q)^2/(p+q)) ((p-q)^2 + 2|1-p.q| p q) / (p+q)^2."""
    num, d, s = _brd_numerator(p, q)
    s = jnp.maximum(s, _EPS)
    return jnp.abs(jnp.sum((d * d / s) * num / (s * s), axis=-1))


def manhattan(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Pairwise L1 distance."""
    pb, qb = _broadcast_pair(p, q)
    return jnp.sum(jnp.abs(pb - qb), axis=-1)


class AbstractDistance:
    """Pluggable distance: callable on (query batch, gallery batch) -> [Q, G].

    Keeps the reference's AbstractDistance boundary (SURVEY.md §2.1) while the
    actual math lives in the pure pairwise functions above. ``__call__`` on
    two single vectors returns a scalar, matching the reference's scalar
    contract; on batches it returns the full pairwise block.
    """

    name: str = "abstract"
    pairwise: PairwiseFn = None  # type: ignore[assignment]

    def __call__(self, p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
        p = jnp.asarray(p)
        q = jnp.asarray(q)
        scalar = p.ndim == 1 and q.ndim == 1
        out = type(self).pairwise(p, q)
        return out[0, 0] if scalar else out

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    # Serialization hooks (utils.serialization registry).
    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, config: dict) -> "AbstractDistance":
        return cls(**config)


class EuclideanDistance(AbstractDistance):
    name = "euclidean"
    pairwise = staticmethod(euclidean)


class SquaredEuclideanDistance(AbstractDistance):
    name = "squared_euclidean"
    pairwise = staticmethod(squared_euclidean)


class CosineDistance(AbstractDistance):
    name = "cosine"
    pairwise = staticmethod(cosine)


class NormalizedCorrelation(AbstractDistance):
    name = "normalized_correlation"
    pairwise = staticmethod(normalized_correlation)


class ChiSquareDistance(AbstractDistance):
    name = "chi_square"
    pairwise = staticmethod(chi_square)


class HistogramIntersection(AbstractDistance):
    name = "histogram_intersection"
    pairwise = staticmethod(histogram_intersection)


class BinRatioDistance(AbstractDistance):
    name = "bin_ratio"
    pairwise = staticmethod(bin_ratio)


class L1BinRatioDistance(AbstractDistance):
    name = "l1_bin_ratio"
    pairwise = staticmethod(l1_bin_ratio)


class ChiSquareBRD(AbstractDistance):
    name = "chi_square_brd"
    pairwise = staticmethod(chi_square_bin_ratio)


class ManhattanDistance(AbstractDistance):
    name = "manhattan"
    pairwise = staticmethod(manhattan)


DISTANCES = {
    cls.name: cls
    for cls in (
        EuclideanDistance,
        SquaredEuclideanDistance,
        CosineDistance,
        NormalizedCorrelation,
        ChiSquareDistance,
        HistogramIntersection,
        BinRatioDistance,
        L1BinRatioDistance,
        ChiSquareBRD,
        ManhattanDistance,
    )
}
