"""Local Binary Pattern operators as pure, batched jnp functions.

Rebuilds the reference's ``facerec/lbp.py`` capability (SURVEY.md §2.1 "LBP
operators": OriginalLBP 3x3, ExtendedLBP circular with bilinear
interpolation, VarLBP variance), TPU-first:

- All operators act on ``[..., H, W]`` float/uint8 images and return
  ``[..., H-2R, W-2R]`` code/variance maps — leading batch dims broadcast
  for free, no per-image Python loops.
- The circular sampling offsets are *static* Python floats (radius and
  neighbor count are plugin constructor args, hence compile-time constants),
  so bilinear interpolation compiles to four static slices + a weighted sum
  per neighbor: pure VPU elementwise work, no gathers, no dynamic shapes.
- Codes are built with comparisons and static bit weights; XLA fuses the
  whole operator into one elementwise kernel.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def original_lbp(x: jnp.ndarray) -> jnp.ndarray:
    """3x3 LBP code map: [..., H, W] -> [..., H-2, W-2] int32 in [0, 255].

    Bit order: clockwise from the top-left neighbor, MSB first (the standard
    original-LBP weighting the reference family uses).
    """
    x = jnp.asarray(x)
    c = x[..., 1:-1, 1:-1]
    neighbors = (
        x[..., 0:-2, 0:-2],  # top-left
        x[..., 0:-2, 1:-1],  # top
        x[..., 0:-2, 2:],    # top-right
        x[..., 1:-1, 2:],    # right
        x[..., 2:, 2:],      # bottom-right
        x[..., 2:, 1:-1],    # bottom
        x[..., 2:, 0:-2],    # bottom-left
        x[..., 1:-1, 0:-2],  # left
    )
    code = jnp.zeros(c.shape, dtype=jnp.int32)
    for i, n in enumerate(neighbors):
        bit = 1 << (7 - i)
        code = code + bit * (n >= c).astype(jnp.int32)
    return code


def _circular_samples(x: jnp.ndarray, radius: int, neighbors: int):
    """Bilinearly-interpolated circular samples around each interior pixel.

    Returns a list of ``neighbors`` arrays shaped [..., H-2r, W-2r]. Sample
    k sits at angle ``2*pi*k/neighbors`` on a circle of ``radius`` around the
    center; all offsets are static so each sample is four static slices.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    h, w = x.shape[-2], x.shape[-1]
    oh, ow = h - 2 * radius, w - 2 * radius
    samples = []
    for k in range(neighbors):
        theta = 2.0 * math.pi * k / neighbors
        # Standard circular-LBP sample point (row offset, col offset).
        dy = -radius * math.sin(theta)
        dx = radius * math.cos(theta)
        fy, fx = math.floor(dy), math.floor(dx)
        ty, tx = dy - fy, dx - fx
        # Bilinear weights over the 4 integer neighbors of (dy, dx).
        w00 = (1 - ty) * (1 - tx)
        w01 = (1 - ty) * tx
        w10 = ty * (1 - tx)
        w11 = ty * tx
        # Window origin for the interior region, shifted by the offset.
        y0 = radius + fy
        x0 = radius + fx

        def win(yy, xx):
            return x[..., yy : yy + oh, xx : xx + ow]

        # Zero-weight taps are skipped: when the sample sits exactly on an
        # integer offset the +1 slice would run past the image edge, and the
        # weights are static Python floats so the skip costs nothing.
        s = None
        for wgt, yy, xx in (
            (w00, y0, x0),
            (w01, y0, x0 + 1),
            (w10, y0 + 1, x0),
            (w11, y0 + 1, x0 + 1),
        ):
            if wgt > 1e-12:
                term = wgt * win(yy, xx)
                s = term if s is None else s + term
        samples.append(s)
    return samples


def extended_lbp(x: jnp.ndarray, radius: int = 1, neighbors: int = 8) -> jnp.ndarray:
    """Circular (extended) LBP: [..., H, W] -> [..., H-2r, W-2r] int32 codes."""
    if neighbors > 31:
        raise ValueError("extended_lbp supports at most 31 neighbors (int32 codes)")
    x = jnp.asarray(x, dtype=jnp.float32)
    c = x[..., radius:-radius, radius:-radius]
    code = jnp.zeros(c.shape, dtype=jnp.int32)
    for k, s in enumerate(_circular_samples(x, radius, neighbors)):
        # Tolerance mirrors the upstream family's >= comparison on floats.
        code = code + (1 << k) * (s >= c).astype(jnp.int32)
    return code


def var_lbp(x: jnp.ndarray, radius: int = 1, neighbors: int = 8) -> jnp.ndarray:
    """Rotation-invariant local variance of the circular samples (VAR operator)."""
    samples = jnp.stack(_circular_samples(x, radius, neighbors), axis=0)
    mean = jnp.mean(samples, axis=0)
    return jnp.mean((samples - mean) ** 2, axis=0)


def lbp_num_bins(neighbors: int = 8) -> int:
    return 1 << neighbors


class LocalBinaryOperator:
    """Pluggable LBP operator (the reference's lbp-operator boundary,
    SURVEY.md §2.1): callable on [..., H, W] images, exposes ``num_bins``
    for the SpatialHistogram feature and config hooks for serialization."""

    name = "abstract_lbp"

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    @property
    def num_bins(self) -> int:
        raise NotImplementedError

    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, config: dict) -> "LocalBinaryOperator":
        return cls(**config)

    def __repr__(self) -> str:
        cfg = ", ".join(f"{k}={v}" for k, v in self.get_config().items())
        return f"{type(self).__name__}({cfg})"


class OriginalLBP(LocalBinaryOperator):
    name = "original_lbp"

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return original_lbp(x)

    @property
    def num_bins(self) -> int:
        return 256


class ExtendedLBP(LocalBinaryOperator):
    name = "extended_lbp"

    def __init__(self, radius: int = 1, neighbors: int = 8):
        self.radius = int(radius)
        self.neighbors = int(neighbors)

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return extended_lbp(x, self.radius, self.neighbors)

    @property
    def num_bins(self) -> int:
        return 1 << self.neighbors

    def get_config(self) -> dict:
        return {"radius": self.radius, "neighbors": self.neighbors}


class VarLBP(LocalBinaryOperator):
    """Variance operator; quantized into ``num_bins`` buckets by the
    SpatialHistogram feature (continuous output, so bins are set here)."""

    name = "var_lbp"

    def __init__(self, radius: int = 1, neighbors: int = 8, bins: int = 64, max_var: float = 8192.0):
        self.radius = int(radius)
        self.neighbors = int(neighbors)
        self.bins = int(bins)
        self.max_var = float(max_var)

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        v = var_lbp(x, self.radius, self.neighbors)
        idx = jnp.clip(v / self.max_var, 0.0, 1.0 - 1e-7) * self.bins
        return idx.astype(jnp.int32)

    @property
    def num_bins(self) -> int:
        return self.bins

    def get_config(self) -> dict:
        return {
            "radius": self.radius,
            "neighbors": self.neighbors,
            "bins": self.bins,
            "max_var": self.max_var,
        }


LBP_OPERATORS = {cls.name: cls for cls in (OriginalLBP, ExtendedLBP, VarLBP)}
