"""Pallas TPU kernel: fused gallery similarity + streaming top-k.

The hot op of the serving path (SURVEY.md §3.4: the reference's
``NearestNeighbor.predict`` "distances to ALL gallery vectors -> argsort"
loop) is a [Q, D] x [D, N] similarity matmul followed by top-k. The XLA
formulation (``parallel.gallery.match_global``) materializes the [Q, N]
score matrix in HBM before ``lax.top_k`` reads it back — at Q=256 over a
1M-row gallery that is a 1 GB f32 round-trip per batch, pure HBM-bandwidth
waste for k<=8 survivors per query.

This kernel streams the gallery through VMEM in [block_n, D] tiles
(flash-attention-style): each grid step computes one [block_q, block_n]
score tile on the MXU and folds it into a running [block_q, k] top-k
accumulator that lives in the output VMEM block across the gallery-tile
grid axis — the [Q, N] matrix never exists anywhere. Scores use bf16
operands with f32 accumulation (MXU native); the merge is k static
max-extract passes on the VPU (k is small and static, so no sort network
is needed).

Used by ``ShardedGallery`` as the single-shard fast path; the XLA
formulation stays both the multi-chip GSPMD path (XLA cannot partition a
custom call across tp shards) and the correctness oracle in tests, which
run this kernel in interpret mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30  # plain float: a jnp scalar would be a captured constant in the kernel


def _match_kernel(q_ref, g_ref, valid_ref, vals_ref, idx_ref, *, k: int,
                  block_n: int):
    """One (query-block, gallery-tile) grid step.

    q_ref [BQ, D]; g_ref [BN, D]; valid_ref [1, BN] f32 (0/1);
    vals_ref/idx_ref [BQ, k] — the running top-k, revisited across the
    gallery-tile grid axis (accumulator pattern: same output block for
    every j, written back after the last visit).
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        vals_ref[:] = jnp.full(vals_ref.shape, NEG_INF, jnp.float32)
        idx_ref[:] = jnp.full(idx_ref.shape, -1, jnp.int32)

    # MXU: bf16 operands, f32 accumulation (same precision split as the
    # XLA path in parallel.gallery.match_global).
    s = jax.lax.dot_general(
        q_ref[:].astype(jnp.bfloat16),
        g_ref[:].astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [BQ, BN]
    s = jnp.where(valid_ref[:] > 0.5, s, NEG_INF)
    bq = s.shape[0]
    col = j * block_n + jax.lax.broadcasted_iota(jnp.int32, (bq, block_n), 1)

    cand_vals = jnp.concatenate([vals_ref[:], s], axis=1)  # [BQ, k+BN]
    cand_idx = jnp.concatenate([idx_ref[:], col], axis=1)
    new_vals, new_idx = [], []
    for _ in range(k):  # k is small and static: unrolled VPU max-extracts
        best = jnp.max(cand_vals, axis=1, keepdims=True)  # [BQ, 1]
        # Deterministic tie-breaking: among candidates at the max value,
        # take the LOWEST gallery index (the running accumulator carries
        # earlier tiles' global indices, so this holds across the whole
        # streamed gallery and matches lax.top_k / a stable argsort — the
        # compiled TPU argmax used before picked an unspecified tied
        # position, measured as idx-parity 0.69 vs XLA on tie-heavy
        # galleries with |sim diff| exactly 0).
        masked_idx = jnp.where(cand_vals == best, cand_idx,
                               jnp.int32(2**31 - 1))
        best_idx = jnp.min(masked_idx, axis=1, keepdims=True)  # [BQ, 1]
        hit = (cand_vals == best) & (cand_idx == best_idx)
        # Sentinel from the VALUE, never from tie-breaking: when all
        # remaining candidates are masked (-1e30), the winner above is
        # whatever index rode the mask value — so a slot whose best is the
        # mask value must emit index -1 explicitly. Real sims are
        # cosine-scale; half the mask magnitude separates them
        # unambiguously.
        best_idx = jnp.where(best > NEG_INF * 0.5, best_idx, -1)
        new_vals.append(best)
        new_idx.append(best_idx)
        cand_vals = jnp.where(hit, NEG_INF, cand_vals)
    vals_ref[:] = jnp.concatenate(new_vals, axis=1)
    idx_ref[:] = jnp.concatenate(new_idx, axis=1)


@functools.partial(
    jax.jit, static_argnames=("k", "block_q", "block_n", "interpret")
)
def streaming_match_topk(q, g, valid, *, k: int = 1, block_q: int = 128,
                         block_n: int = 512, interpret: bool = False):
    """Top-k cosine/dot similarity of queries against a gallery, streamed.

    q [Q, D] float; g [N, D] float; valid [N] bool/0-1 mask.
    Returns (sims [Q, k] f32, indices [Q, k] int32); invalid rows never
    surface. Equal similarities break toward the LOWEST gallery index —
    the same order as ``lax.top_k`` and a stable argsort — so parity with
    the XLA matcher is exact even on tie-heavy (duplicate-row) galleries. When fewer than k valid rows exist, the empty slots carry
    sim -1e30 and the explicit sentinel index **-1** (derived from the
    value in-kernel, so it holds in compiled mode too) — callers gathering
    labels must mask ``idx < 0`` (see ``parallel.gallery``). Q and N are
    padded up to block multiples here, so any sizes work; D should be
    modest (fits VMEM with the tiles).
    """
    q = jnp.asarray(q, jnp.float32)
    # Keep a bf16-stored gallery in bf16: the kernel casts both operands
    # to bf16 for the MXU anyway (see _match_kernel), so upcasting here
    # would only double the HBM traffic this streaming kernel exists to
    # save. Other dtypes go to f32 as before.
    if g.dtype != jnp.bfloat16:
        g = jnp.asarray(g, jnp.float32)
    qn, d = q.shape
    n = g.shape[0]
    block_q = min(block_q, max(8, int(np.ceil(qn / 8) * 8)))
    block_n = min(block_n, n) if n >= 128 else n
    q_pad = (-qn) % block_q
    n_pad = (-n) % block_n
    if q_pad:
        q = jnp.pad(q, ((0, q_pad), (0, 0)))
    if n_pad:
        g = jnp.pad(g, ((0, n_pad), (0, 0)))
    validf = jnp.pad(
        jnp.asarray(valid, jnp.float32), (0, n_pad)
    ).reshape(1, -1)
    grid = (q.shape[0] // block_q, g.shape[0] // block_n)
    vals, idx = pl.pallas_call(
        functools.partial(_match_kernel, k=k, block_n=block_n),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_q, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i, j: (j, 0)),
            pl.BlockSpec((1, block_n), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
            pl.BlockSpec((block_q, k), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q.shape[0], k), jnp.float32),
            jax.ShapeDtypeStruct((q.shape[0], k), jnp.int32),
        ],
        interpret=interpret,
    )(q, g, validf)
    return vals[:qn], idx[:qn]
