"""Pallas TPU kernel: one fused depthwise-separable embedder block.

Why (SURVEY.md §6 embed-stage MFU 0.0998; VERDICT r4 #6): the serving
embedder's stages are ``_SepBlock``s — dw3x3 -> GroupNorm -> relu -> pw1x1
-> GroupNorm -> (+residual) -> relu. Under XLA each of those is its own
HLO: the depthwise conv lowers as a C-group grouped convolution (a known
weak lowering on TPU — the MXU wants dense contractions, so grouped convs
shred into per-channel slivers), and every op boundary round-trips the
[B, H, W, C] activation through HBM. Every *training-visible* structural
fix was measured and accuracy-rejected in round 4
(scripts/.gate_embedder.jsonl), so this kernel changes the SCHEDULE, not
the math: the whole block runs in one pallas call per batch tile, the
activation stays in VMEM end-to-end, the depthwise conv is 9 statically
unrolled shifted fused-multiply-adds on the VPU (no grouped-conv
lowering), and the pointwise conv is a single dense [B*H*W, C] x [C, F]
MXU contraction.

In-kernel choices that dodge Mosaic's weak spots:
- the 3x3 SAME padding happens OUTSIDE the kernel (XLA pad fuses into the
  producer; Mosaic concatenate support is not relied on);
- GroupNorm stats avoid minor-dim reshapes (lane-layout hostile): spatial
  sums reduce to [B, C], then a [C, G] one-hot matmul folds channels into
  groups, and the inverse matmul broadcasts group stats back per channel;
- stats in f32 with fast variance (E[x^2] - E[x]^2), epsilon inside the
  sqrt — matching flax.linen.GroupNorm's defaults, validated by the
  equivalence tests in tests/test_pallas_sepblock.py.

Numerics vs the flax block: flax computes the convs in bf16 (f32
accumulation) and keeps bf16 activations between ops; this kernel keeps
the activation in f32 VMEM between the fused stages and rounds where flax
rounds the MXU inputs (dw/pw operands in bf16). Differences are bounded by
bf16 rounding noise — the equivalence test pins cosine > 0.9999 on final
embeddings — and the transform is serving-only: training still runs the
flax graph, so the accuracy gate's numbers are untouched.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _group_matrix(c: int, groups: int):
    """[C, G] one-hot: channel -> its GroupNorm group (flax grouping:
    channel // (C/G))."""
    gidx = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 0) // (c // groups)
    g = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 1)
    return (gidx == g).astype(jnp.float32)


def _groupnorm(x, scale, bias, groups: int, eps: float):
    """GroupNorm over (H, W, C/G) per sample, [B, H, W, C] f32 in/out,
    reshape-free (see module docstring)."""
    b, h, w, c = x.shape
    m = _group_matrix(c, groups)
    cnt = h * w * (c // groups)
    s = jnp.sum(x, axis=(1, 2))  # [B, C]
    ss = jnp.sum(x * x, axis=(1, 2))
    gs = jax.lax.dot_general(s, m, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [B, G]
    gss = jax.lax.dot_general(ss, m, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    mean = gs / cnt
    var = jnp.maximum(gss / cnt - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + eps)
    # broadcast group stats back to channels: [B, G] @ [G, C]
    mean_c = jax.lax.dot_general(mean, m.T, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    inv_c = jax.lax.dot_general(inv, m.T, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    y = (x - mean_c[:, None, None, :]) * inv_c[:, None, None, :]
    return y * scale[None, None, None, :] + bias[None, None, None, :]


def _sepblock_kernel(*refs, stride: int, groups: int,
                     eps: float, residual: bool, out_h: int, out_w: int):
    """One batch tile: the whole separable block, VMEM-resident.

    Refs: [x_ref only when residual] xin_ref, wdw_ref, g1s_ref, g1b_ref,
    wpw_ref, g2s_ref, g2b_ref, out_ref. x_ref [Bb, H, W, C] is the
    residual source and is only an input at all when the block HAS a
    residual — shipping it HBM->VMEM on the stride-2 stage heads would be
    dead bandwidth on the exact path this kernel exists to speed up.

    xin_ref is the SAME-padded dw input in a stride-dependent layout:
    stride 1 -> [Bb, H+2, W+2, C] (taps are plain unstrided slices);
    stride 2 -> [Bb, 4, (H+2)/2, (W+2)/2, C], the four even/odd phase
    planes of the padded input (phase index = (y%2)*2 + x%2), built by
    XLA. Mosaic rejects strided vector slices (the r5 on-chip A/B died
    with 'expected strides to be confined to [1, 2)'), so the stride-2
    tap (dy, dx) instead reads phase (dy%2, dx%2) at offset
    (dy//2, dx//2) — an unstrided slice of a phase plane.
    wdw_ref [3, 3, C]; wpw_ref [C, F]; out_ref [Bb, out_h, out_w, F].
    """
    if residual:
        (x_ref, xin_ref, wdw_ref, g1s_ref, g1b_ref, wpw_ref, g2s_ref,
         g2b_ref, out_ref) = refs
    else:
        (xin_ref, wdw_ref, g1s_ref, g1b_ref, wpw_ref, g2s_ref,
         g2b_ref, out_ref) = refs
    xin = xin_ref[:].astype(jnp.float32)
    wdw = wdw_ref[:].astype(jnp.float32)
    bb = xin_ref.shape[0]
    c = xin_ref.shape[-1]

    # depthwise 3x3 as 9 unrolled shifted FMAs (VPU); bf16-round the
    # operands once, accumulate f32 — mirrors the MXU's bf16xbf16->f32.
    acc = jnp.zeros((bb, out_h, out_w, c), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            if stride == 1:
                patch = jax.lax.slice(
                    xin, (0, dy, dx, 0), (bb, dy + out_h, dx + out_w, c))
            else:
                ph_idx = (dy % 2) * 2 + (dx % 2)
                i0, j0 = dy // 2, dx // 2
                patch = jax.lax.slice(
                    xin,
                    (0, ph_idx, i0, j0, 0),
                    (bb, ph_idx + 1, i0 + out_h, j0 + out_w, c),
                ).reshape(bb, out_h, out_w, c)
            patch = patch.astype(jnp.bfloat16).astype(jnp.float32)
            w = wdw[dy, dx, :].astype(jnp.bfloat16).astype(jnp.float32)
            acc = acc + patch * w[None, None, None, :]

    h1 = jnp.maximum(_groupnorm(acc, g1s_ref[:].astype(jnp.float32),
                                g1b_ref[:].astype(jnp.float32), groups, eps),
                     0.0)

    # pointwise 1x1: one dense MXU contraction over channels
    f = wpw_ref.shape[1]
    h1f = h1.reshape(bb * out_h * out_w, c)
    pw = jax.lax.dot_general(
        h1f.astype(jnp.bfloat16), wpw_ref[:].astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    ).reshape(bb, out_h, out_w, f)

    h2 = _groupnorm(pw, g2s_ref[:].astype(jnp.float32),
                    g2b_ref[:].astype(jnp.float32), groups, eps)
    if residual:
        h2 = h2 + x_ref[:].astype(jnp.float32)
    out_ref[:] = jnp.maximum(h2, 0.0).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "stride", "groups", "eps", "residual", "block_b", "interpret"))
def fused_sep_block(x, w_dw, g1_scale, g1_bias, w_pw, g2_scale, g2_bias, *,
                    stride: int = 1, groups: int = 4, eps: float = 1e-6,
                    residual: bool = False, block_b: int = 8,
                    interpret: bool = False):
    """One ``_SepBlock`` forward, fused (see module docstring).

    x [B, H, W, C]; w_dw [3, 3, 1, C] (flax depthwise kernel layout);
    w_pw [1, 1, C, F]; GroupNorm scales/biases [C] / [F].
    Returns [B, H/stride, W/stride, F] in x.dtype. ``residual`` must match
    the flax block's condition (stride == 1 and C == F).
    """
    b, h, w, c = x.shape
    if residual and (stride != 1 or w_pw.shape[2] != w_pw.shape[3]):
        raise ValueError("residual requires stride 1 and C == F")
    if stride == 2 and (h % 2 or w % 2):
        # flax SAME stride-2 gives ceil(h/2); this kernel's slicing scheme
        # assumes even dims (floor == ceil). Raise rather than silently
        # diverge from the training graph.
        raise ValueError(f"stride-2 fused block needs even spatial dims, got {h}x{w}")
    out_h, out_w = h // stride, w // stride
    f = w_pw.shape[3]
    # SAME padding for the dw conv, applied in XLA (fuses upstream):
    # stride 1 -> (1, 1); stride 2 over even H -> (0, 1). The kernel slices
    # from offset 0 either way, so stride 2 pads (0, 2) and ignores the
    # last row/col; stride 1 pads (1, 1).
    pad_lo = 1 if stride == 1 else 0
    pad_hi = 2 - pad_lo

    block_b = max(1, min(block_b, b))
    b_pad = (-b) % block_b
    if b_pad:
        x = jnp.pad(x, ((0, b_pad), (0, 0), (0, 0), (0, 0)))
    xpad = jnp.pad(x, ((0, 0), (pad_lo, pad_hi), (pad_lo, pad_hi), (0, 0)))
    if stride == 1:
        xin = xpad
        xin_spec = pl.BlockSpec((block_b, h + 2, w + 2, c),
                                lambda i: (i, 0, 0, 0))
    else:
        # Even/odd phase decomposition in XLA (strided slices are fine
        # here; they are NOT inside the kernel — Mosaic rejects them, see
        # _sepblock_kernel docstring). [B, 4, (H+2)/2, (W+2)/2, C].
        xin = jnp.stack([xpad[:, a::2, b2::2, :]
                         for a in (0, 1) for b2 in (0, 1)], axis=1)
        xin_spec = pl.BlockSpec(
            (block_b, 4, (h + 2) // 2, (w + 2) // 2, c),
            lambda i: (i, 0, 0, 0, 0))
    grid = (x.shape[0] // block_b,)

    full = lambda *s: pl.BlockSpec(s, lambda i: (0,) * len(s))  # noqa: E731
    # x (the residual source) is only an input when the block has a
    # residual: stride-2 stage heads skip the dead HBM->VMEM copy.
    in_specs = [
        xin_spec,
        full(3, 3, c),
        full(c), full(c),
        full(c, f),
        full(f), full(f),
    ]
    inputs = [xin, w_dw[:, :, 0, :], g1_scale, g1_bias, w_pw[0, 0],
              g2_scale, g2_bias]
    if residual:
        in_specs.insert(0, pl.BlockSpec((block_b, h, w, c),
                                        lambda i: (i, 0, 0, 0)))
        inputs.insert(0, x)
    out = pl.pallas_call(
        functools.partial(
            _sepblock_kernel, stride=stride, groups=groups, eps=eps,
            residual=residual, out_h=out_h, out_w=out_w,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_b, out_h, out_w, f),
                               lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], out_h, out_w, f), x.dtype),
        interpret=interpret,
    )(*inputs)
    return out[:b]
