"""Eigen-solvers for the subspace features: PCA (Eigenfaces), LDA, Fisherfaces.

TPU replacement for the reference's imported LAPACK surface (SURVEY.md §2.2:
``numpy.linalg.eigh/svd`` used by ``facerec/feature.py`` PCA/LDA fits). All
fits run on device via ``jnp.linalg.eigh``; the classic small-matrix
(Gram) trick keeps the eigenproblem at [N, N] when D >> N, which is the
Eigenfaces regime (70*70 = 4900 pixels, N a few hundred images).

Numerical note (SURVEY.md §7 "hard parts"): fits default to float32 on
device. Tests compare subspace projections (not raw eigenvector signs)
against NumPy/sklearn oracles with f32 tolerances.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# Subspace math runs at full f32 precision: these are small matmuls where
# eigh conditioning and projection accuracy dominate, and the default
# (backend-chosen) precision was observed to drift ~1e-3 between separate
# compilations of the same projection.
_HI = jax.lax.Precision.HIGHEST


def _mm(a, b):
    return jnp.matmul(a, b, precision=_HI)


class PCAState(NamedTuple):
    mean: jnp.ndarray  # [D]
    components: jnp.ndarray  # [D, K] column eigenvectors, descending eigenvalue
    eigenvalues: jnp.ndarray  # [K]


class LDAState(NamedTuple):
    components: jnp.ndarray  # [D, K]
    eigenvalues: jnp.ndarray  # [K]


def pca_fit(x: jnp.ndarray, num_components: int) -> PCAState:
    """Fit PCA on row-matrix ``x`` [N, D], keep top ``num_components``.

    Uses eigh of the [N, N] Gram matrix when D > N (the Eigenfaces
    small-matrix trick, SURVEY.md §3.1), else eigh of the [D, D] covariance.
    ``num_components`` must be a static positive int (<= min(N, D)).
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    n, d = x.shape
    k = int(num_components)
    if k <= 0 or k > min(n, d):
        raise ValueError(f"num_components={k} must be in [1, min(N={n}, D={d})]")
    mean = jnp.mean(x, axis=0)
    xc = x - mean
    if d > n:
        gram = _mm(xc, xc.T)  # [N, N]
        evals, evecs = jnp.linalg.eigh(gram)
        # eigh returns ascending order; take top-k from the end.
        evals = evals[::-1][:k]
        evecs = evecs[:, ::-1][:, :k]
        comps = _mm(xc.T, evecs)  # [D, k], unnormalized
        comps = comps / jnp.maximum(jnp.linalg.norm(comps, axis=0, keepdims=True), 1e-12)
    else:
        cov = _mm(xc.T, xc)  # [D, D]
        evals, evecs = jnp.linalg.eigh(cov)
        evals = evals[::-1][:k]
        comps = evecs[:, ::-1][:, :k]
    # Eigenvalues of the scatter matrix Xc^T Xc (Gram and covariance paths agree).
    return PCAState(mean=mean, components=comps, eigenvalues=jnp.maximum(evals, 0.0))


def pca_project(state: PCAState, x: jnp.ndarray) -> jnp.ndarray:
    """[..., D] -> [..., K]: W^T (x - mean); one MXU matmul for batches."""
    return _mm(jnp.asarray(x, jnp.float32) - state.mean, state.components)


def pca_reconstruct(state: PCAState, z: jnp.ndarray) -> jnp.ndarray:
    """[..., K] -> [..., D] back-projection (for eigenface visualization)."""
    return _mm(z, state.components.T) + state.mean


def lda_fit(
    x: jnp.ndarray, y: jnp.ndarray, num_classes: int, num_components: int, reg: float = 1e-4
) -> LDAState:
    """Fisher LDA on row-matrix ``x`` [N, D] with int labels ``y`` [N].

    Solves the generalized eigenproblem Sb v = λ Sw v via Cholesky whitening
    of the (regularized) within-class scatter — eigh-only, so it stays on
    device and differentiable. ``num_classes`` and ``num_components`` are
    static; labels must be in [0, num_classes).

    Class means are computed with a one-hot matmul (no segment_sum /
    dynamic shapes), so the whole fit is three matmuls + one eigh.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    y = jnp.asarray(y, dtype=jnp.int32)
    n, d = x.shape
    c = int(num_classes)
    k = int(num_components)
    if k <= 0 or k > c - 1:
        raise ValueError(f"num_components={k} must be in [1, num_classes-1={c - 1}]")
    onehot = (y[:, None] == jnp.arange(c)[None, :]).astype(jnp.float32)  # [N, C]
    counts = jnp.sum(onehot, axis=0)  # [C]
    safe_counts = jnp.maximum(counts, 1.0)
    class_means = (onehot.T @ x) / safe_counts[:, None]  # [C, D]
    total_mean = jnp.mean(x, axis=0)
    # Within-class scatter: sum over samples of (x - mean_class)(x - mean_class)^T.
    centered = x - onehot @ class_means  # [N, D]
    sw = _mm(centered.T, centered)
    # Between-class scatter: sum_c n_c (mu_c - mu)(mu_c - mu)^T.
    md = class_means - total_mean
    sb = _mm((md * counts[:, None]).T, md)
    # Regularize Sw for Cholesky (f32 + singular scatter in the PCA'd space).
    sw = sw + reg * jnp.trace(sw) / d * jnp.eye(d, dtype=jnp.float32)
    chol = jnp.linalg.cholesky(sw)
    # M = L^-1 Sb L^-T is symmetric PSD; eigh it, map back by L^-T.
    linv_sb = jnp.linalg.solve(chol, sb)
    m = jnp.linalg.solve(chol, linv_sb.T).T
    m = 0.5 * (m + m.T)
    evals, evecs = jnp.linalg.eigh(m)
    evals = evals[::-1][:k]
    evecs = evecs[:, ::-1][:, :k]
    # Back-substitute: v = L^-T u  <=>  L^T v = u.
    comps = jnp.linalg.solve(chol.T, evecs)
    comps = comps / jnp.maximum(jnp.linalg.norm(comps, axis=0, keepdims=True), 1e-12)
    return LDAState(components=comps, eigenvalues=jnp.maximum(evals, 0.0))


def lda_project(state: LDAState, x: jnp.ndarray) -> jnp.ndarray:
    return _mm(jnp.asarray(x, jnp.float32), state.components)
