"""Image ops as pure jnp functions: the TPU replacement for the reference's
imported OpenCV C++ surface (SURVEY.md §2.2: cv2.resize / cvtColor /
equalizeHist) and its preprocessing plugins (SURVEY.md §2.1
"Preprocessing": TanTriggs, HistogramEqualization, Resize, minmax).

All functions take ``[..., H, W]`` (grayscale) or ``[..., H, W, 3]`` (RGB)
float arrays and broadcast over leading batch dims, so the whole
preprocessing chain stays inside one jitted graph — no host round-trips per
frame.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

# BT.601 luma weights — matches cv2.cvtColor(BGR2GRAY) up to channel order.
_LUMA_RGB = (0.299, 0.587, 0.114)


def to_grayscale(x: jnp.ndarray, channel_order: str = "rgb") -> jnp.ndarray:
    """[..., H, W, 3] -> [..., H, W] luma; a dot product the VPU eats for free."""
    x = jnp.asarray(x, dtype=jnp.float32)
    w = jnp.array(_LUMA_RGB if channel_order == "rgb" else _LUMA_RGB[::-1], dtype=jnp.float32)
    return x @ w


def resize(x: jnp.ndarray, size: Tuple[int, int], method: str = "bilinear") -> jnp.ndarray:
    """Resize trailing [H, W] dims to ``size=(h, w)``; batch dims untouched.

    Identity sizes return the input unchanged — the serving graph calls
    this on crops that are already at ``face_size``, and an identity
    ``jax.image.resize`` is NOT free (it still emits the resample)."""
    x = jnp.asarray(x, dtype=jnp.float32)
    out_shape = x.shape[:-2] + tuple(size)
    if out_shape == x.shape:
        return x
    return jax.image.resize(x, out_shape, method=method)


def minmax_normalize(x: jnp.ndarray, low: float = 0.0, high: float = 1.0) -> jnp.ndarray:
    """Per-image min/max normalization over the trailing [H, W] dims."""
    x = jnp.asarray(x, dtype=jnp.float32)
    mn = jnp.min(x, axis=(-2, -1), keepdims=True)
    mx = jnp.max(x, axis=(-2, -1), keepdims=True)
    scale = (high - low) / jnp.maximum(mx - mn, 1e-12)
    return low + (x - mn) * scale


def histogram_equalize(x: jnp.ndarray, num_bins: int = 256) -> jnp.ndarray:
    """Per-image histogram equalization, jittable (one-hot histogram + cumsum LUT).

    Input is expected in [0, 255] (any float range works: it is first
    quantized to ``num_bins`` levels over [0, 255]). Output is float32 in
    [0, 255], matching cv2.equalizeHist semantics closely enough for the
    preprocessing chain.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    h, w = x.shape[-2], x.shape[-1]
    n = h * w
    idx = jnp.clip(x, 0.0, 255.0) * ((num_bins - 1) / 255.0)
    idx = jnp.round(idx).astype(jnp.int32)
    flat = idx.reshape(x.shape[:-2] + (n,))
    # Histogram via scatter-add, O(n) memory per image — a one-hot matmul
    # here would materialize [.., H*W, num_bins] (64 MB f32 for one 256x256
    # frame), a trap as soon as this runs on frames rather than 70x70 crops.
    def _hist_1d(f):
        return jnp.zeros((num_bins,), jnp.float32).at[f].add(1.0)

    hist = jax.vmap(_hist_1d)(flat.reshape((-1, n))).reshape(
        x.shape[:-2] + (num_bins,)
    )
    cdf = jnp.cumsum(hist, axis=-1)
    cdf_min = jnp.take_along_axis(
        cdf, jnp.argmax((hist > 0).astype(jnp.int32), axis=-1)[..., None], axis=-1
    )
    denom = jnp.maximum(n - cdf_min, 1.0)
    lut = jnp.clip((cdf - cdf_min) / denom * 255.0, 0.0, 255.0)
    out = jnp.take_along_axis(lut, flat, axis=-1)
    return out.reshape(x.shape)


def _gaussian_kernel_1d(sigma: float) -> jnp.ndarray:
    """Static-size separable Gaussian taps (radius = ceil(3 sigma))."""
    radius = max(1, int(math.ceil(3.0 * sigma)))
    xs = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-(xs**2) / (2.0 * sigma * sigma))
    return k / jnp.sum(k)


def gaussian_blur(x: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Separable Gaussian blur over trailing [H, W], 'same' size, edge-replicate.

    Implemented as two 1-D convolutions with static kernels so XLA lowers
    them to small dense convs (MXU-friendly) instead of a generic stencil.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    k = _gaussian_kernel_1d(sigma)
    r = (k.shape[0] - 1) // 2
    batch_shape = x.shape[:-2]
    h, w = x.shape[-2], x.shape[-1]
    xb = x.reshape((-1, h, w))

    def conv_last(a: jnp.ndarray) -> jnp.ndarray:
        # a: [N, L, M]; convolve along M with edge padding.
        ap = jnp.pad(a, ((0, 0), (0, 0), (r, r)), mode="edge")
        # [N, L, M + 2r] -> conv via jnp stacked slices (static taps).
        out = jnp.zeros_like(a)
        for i in range(2 * r + 1):
            out = out + k[i] * ap[:, :, i : i + a.shape[-1]]
        return out

    xb = conv_last(xb)  # along W
    xb = conv_last(xb.swapaxes(-1, -2)).swapaxes(-1, -2)  # along H
    return xb.reshape(batch_shape + (h, w))


def tan_triggs(
    x: jnp.ndarray,
    alpha: float = 0.1,
    tau: float = 10.0,
    gamma: float = 0.2,
    sigma0: float = 1.0,
    sigma1: float = 2.0,
) -> jnp.ndarray:
    """Tan-Triggs illumination normalization (gamma -> DoG -> contrast eq).

    Default parameters follow the facerec-family defaults as reconstructed in
    SURVEY.md §2.1 (reference mount empty — defaults tagged [U] there).
    Output is zero-centered, tau-bounded (tanh stage), float32.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    # Gamma correction (input shifted to >= 1 to keep the power stable).
    xg = jnp.power(x + 1.0, gamma)
    # Difference of Gaussians.
    dog = gaussian_blur(xg, sigma0) - gaussian_blur(xg, sigma1)
    # Two-stage contrast equalization.
    axes = (-2, -1)
    m1 = jnp.mean(jnp.abs(dog) ** alpha, axis=axes, keepdims=True)
    dog = dog / jnp.maximum(m1, 1e-12) ** (1.0 / alpha)
    m2 = jnp.mean(jnp.minimum(jnp.abs(dog), tau) ** alpha, axis=axes, keepdims=True)
    dog = dog / jnp.maximum(m2, 1e-12) ** (1.0 / alpha)
    return tau * jnp.tanh(dog / tau)


def batched_crop_resize(
    frames: jnp.ndarray, boxes: jnp.ndarray, size: Tuple[int, int]
) -> jnp.ndarray:
    """Crop+resize K dynamic boxes per frame, fully on device.

    frames [N, H, W], boxes [N, K, 4] pixel (y0, x0, y1, x1) -> crops
    [N, K, h, w]. The align stage of detect->align->embed->match: boxes are
    *values* (dynamic), so this is bilinear sampling on a computed grid.
    Out-of-bounds samples clamp to the frame edge; degenerate boxes produce
    edge-pixel fills (harmless — such slots are masked invalid downstream).

    TPU-native formulation: bilinear crop+resize is SEPARABLE, so instead of
    four 2-D gathers (measured 167 ms/batch on the real chip — gathers are
    the single slowest primitive on TPU and dominated the whole serving
    graph), build per-crop tent-weight interpolation matrices and run two
    dense matmuls on the MXU:

        crop[k] = Ay[k] @ frame @ Ax[k]^T,
        Ay[k][i, y] = max(0, 1 - |ys[k, i] - y|)   (rows: output pixels)

    Each Ay row has at most two nonzeros — exactly the two bilinear taps —
    so this computes the identical result; the clamped edge case lands all
    weight on the edge pixel, same as clamped gathers. ~5.5 GFLOP per
    32x8-crop batch instead of 12.8M scattered loads: measured 167 ms ->
    sub-ms on the same graph.
    """
    frames = jnp.asarray(frames, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    n, h, w = frames.shape
    oh, ow = size
    # Sample centers of `oh x ow` pixels spanning each box.
    ty = (jnp.arange(oh, dtype=jnp.float32) + 0.5) / oh  # [oh] in (0, 1)
    tx = (jnp.arange(ow, dtype=jnp.float32) + 0.5) / ow
    y0, x0, y1, x1 = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    ys = y0[..., None] + (y1 - y0)[..., None] * ty[None, None, :] - 0.5  # [N, K, oh]
    xs = x0[..., None] + (x1 - x0)[..., None] * tx[None, None, :] - 0.5  # [N, K, ow]
    ys = jnp.clip(ys, 0.0, h - 1.0)
    xs = jnp.clip(xs, 0.0, w - 1.0)
    # Tent-weight interpolation matrices (<= 2 nonzeros per row).
    ay = jnp.maximum(
        0.0, 1.0 - jnp.abs(ys[..., None] - jnp.arange(h, dtype=jnp.float32))
    )  # [N, K, oh, H]
    ax = jnp.maximum(
        0.0, 1.0 - jnp.abs(xs[..., None] - jnp.arange(w, dtype=jnp.float32))
    )  # [N, K, ow, W]
    # Two MXU contractions; f32 accumulation keeps bit-parity with the
    # gather formulation (each contraction only ever sums 2 nonzero taps).
    tmp = jnp.einsum(
        "nkih,nhw->nkiw", ay, frames, precision=jax.lax.Precision.HIGHEST
    )
    return jnp.einsum(
        "nkiw,nkjw->nkij", tmp, ax, precision=jax.lax.Precision.HIGHEST
    )


def crop_and_resize(
    frame: jnp.ndarray, box: Sequence[int], size: Tuple[int, int]
) -> jnp.ndarray:
    """Crop [y0:y1, x0:x1] from a [H, W] frame and resize to ``size``.

    Host-side convenience for the serving path (boxes are dynamic there; the
    batched on-device equivalent uses fixed-size aligned crops).
    """
    y0, x0, y1, x1 = (int(v) for v in box)
    return resize(frame[..., y0:y1, x0:x1], size)
