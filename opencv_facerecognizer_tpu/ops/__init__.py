"""Pure jittable device math primitives (SURVEY.md §1 L1, §7 stage 1)."""

from opencv_facerecognizer_tpu.ops import distance, histogram, image, lbp, linalg

__all__ = ["distance", "histogram", "image", "lbp", "linalg"]
