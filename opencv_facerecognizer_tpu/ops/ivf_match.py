"""Two-stage IVF match: centroid shortlist -> exact Pallas rerank.

The device-side half of the million-identity gallery subsystem
(``parallel.quantizer`` owns the state; this module owns the math). The
"shortlist + exact rerank" structure follows PAPERS.md's *Fast Matching by
2 Lines of Code for Large Scale Face Recognition Systems* (1302.7180):

- **Stage 1** scores the query batch against the ``nlist`` k-means
  centroids (one tiny bf16 matmul: Q x nlist x D, ~1000x smaller than the
  gallery scan) and shortlists each query's top-``nprobe`` cells.
- **Stage 2** takes the batch-level UNION of shortlisted cells — cells
  gather as dense [max_cell, D] int8 blocks because the inverted lists
  are cell-resident — dequantizes them into one padded candidate bucket,
  appends the always-scanned spill, and reranks the bucket with the
  EXISTING exact streaming kernel (``ops.pallas_match.
  streaming_match_topk``). One kernel call serves the whole query batch.

Tie-breaking: the bucket is ordered by ascending gallery row id before
the kernel runs, so the kernel's deterministic lowest-LOCAL-index
tie-break (PR-2) is exactly a lowest-GALLERY-index tie-break — duplicate
rows quantize identically, score identically, and resolve to the same
winner the brute-force scan picks.

Cost model (why the union): per query the candidate set is ~``nprobe *
max_cell`` rows; the union dedups cells shared across the batch and lets
the bucket gather run as contiguous cell blocks instead of per-query
scattered row reads. Against a capacity-C gallery the exact scan streams
``C * D`` bytes per batch; the two-stage path streams ``nlist * D``
(stage 1) + ``|union| * max_cell * D`` int8 bytes — sublinear in C once
``nlist`` scales with sqrt(C) (the ``bench.py`` ivf ladder measures the
crossover; the recall gate in tests pins the accuracy side).

Also home to the **tie-aware comparators** used by the recall gate and by
``bench.py``'s kernel-parity check: BENCH_r05 reported ``idx match
0.6914`` with ``max |sim diff| 0.00e+00`` — pure tie-position divergence
counted as error. A comparison between two matchers is only meaningful
modulo ties: any index attaining the max similarity is a correct answer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk

_INT32_MAX = np.int32(2**31 - 1)


def ivf_match_topk(q, valid, ivf, *, k: int = 1, nprobe: int = 8,
                   interpret: bool = False):
    """Two-stage top-k over an IVF-quantized gallery.

    q [Q, D] float queries; valid [capacity] bool — the GALLERY's validity
    mask (row ids in the lists index into it); ivf — an
    ``parallel.quantizer.IVFDeviceData`` (or any 7-tuple of its fields).
    Returns (sims [Q, k] f32, gallery row indices [Q, k] int32) with the
    same sentinel contract as ``streaming_match_topk``: empty slots carry
    sim -1e30 and index -1. Traceable under jit; every intermediate shape
    is static (union size = min(nlist, Q * nprobe) cells).
    """
    (centroids, cell_rows, cell_q8, cell_scale,
     spill_rows, spill_q8, spill_scale) = tuple(ivf)[:7]
    q = jnp.asarray(q, jnp.float32)
    qn = q.shape[0]
    nlist, max_cell, d = cell_q8.shape
    p = min(int(nprobe), nlist)

    # ---- stage 1: query-vs-centroid scores -> per-query top-P cells ----
    scores = jax.lax.dot_general(
        q.astype(jnp.bfloat16), centroids.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )  # [Q, nlist]
    _, cells = jax.lax.top_k(scores, p)  # [Q, P]

    # ---- batch union of shortlisted cells, ascending cell id ----
    # Static size U >= the number of distinct probed cells, so no query's
    # cell is ever dropped; unprobed slots pad with the sentinel nlist.
    u = min(nlist, qn * p)
    mark = jnp.zeros((nlist,), bool).at[cells.reshape(-1)].set(True)
    sel_key = jnp.where(mark, jnp.arange(nlist, dtype=jnp.int32),
                        jnp.int32(nlist))
    sel = jnp.sort(sel_key)[:u]  # [U] probed cell ids first, pads last
    pad_cell = sel >= nlist
    selc = jnp.minimum(sel, nlist - 1)

    # ---- gather cell-resident blocks + spill into one bucket ----
    ids = jnp.where(jnp.repeat(pad_cell, max_cell),
                    jnp.int32(-1), cell_rows[selc].reshape(u * max_cell))
    all_ids = jnp.concatenate([ids, spill_rows])
    all_q8 = jnp.concatenate([cell_q8[selc].reshape(u * max_cell, d),
                              spill_q8])
    all_scale = jnp.concatenate([cell_scale[selc].reshape(u * max_cell),
                                 spill_scale])
    # Ascending gallery row id: the exact kernel's lowest-local-index
    # tie-break becomes a lowest-gallery-index tie-break (pads sort last).
    order = jnp.argsort(jnp.where(all_ids < 0, _INT32_MAX, all_ids))
    all_ids = jnp.take(all_ids, order)
    bucket = (jnp.take(all_q8, order, axis=0).astype(jnp.bfloat16)
              * jnp.take(all_scale, order).astype(jnp.bfloat16)[:, None])
    # Bounds-mask, never clip: a list entry whose id exceeds THIS gallery
    # snapshot's capacity (the reader paired a fresher quantizer with an
    # older same-epoch gallery snapshot across a concurrent grow) must be
    # skipped — a clipped gather would score row capacity-1 and report
    # its label for a different row entirely.
    in_range = (all_ids >= 0) & (all_ids < valid.shape[0])
    bvalid = in_range & jnp.take(valid, jnp.clip(all_ids, 0,
                                                 valid.shape[0] - 1))

    # ---- stage 2: exact rerank with the existing streaming kernel ----
    vals, lidx = streaming_match_topk(q, bucket, bvalid, k=k,
                                      interpret=interpret)
    gidx = jnp.where(lidx < 0, jnp.int32(-1),
                     jnp.take(all_ids, jnp.maximum(lidx, 0)))
    return vals, gidx


# ---- tie-aware matcher comparison (shared by bench.py and the tests) ----

def tie_aware_mismatch(vals_a, idx_a, vals_b, idx_b,
                       atol: float = 2e-2) -> np.ndarray:
    """Boolean mask of REAL top-1 disagreements between two matchers.

    A row disagrees only when the indices differ AND the similarities the
    two matchers report for their own winners differ beyond ``atol`` —
    equal-valued different indices are ties, and any index attaining the
    max similarity is a correct answer (the BENCH_r05 ``idx match
    0.6914 / |sim diff| 0.00e+00`` artifact was exactly tie positions
    counted as errors). Accepts [Q] or [Q, 1] shaped columns.
    """
    vals_a = np.asarray(vals_a, np.float32).reshape(-1)
    vals_b = np.asarray(vals_b, np.float32).reshape(-1)
    idx_a = np.asarray(idx_a).reshape(-1)
    idx_b = np.asarray(idx_b).reshape(-1)
    return (idx_a != idx_b) & (np.abs(vals_a - vals_b) > atol)


def tie_aware_agreement(vals_a, idx_a, vals_b, idx_b,
                        atol: float = 2e-2) -> float:
    """Fraction of rows whose top-1 agrees modulo ties — the comparator
    behind both the bench parity metric and the IVF recall gate (recall
    == agreement of the two-stage result against tie-aware brute force).
    """
    mism = tie_aware_mismatch(vals_a, idx_a, vals_b, idx_b, atol=atol)
    return float(1.0 - mism.mean()) if mism.size else 1.0
