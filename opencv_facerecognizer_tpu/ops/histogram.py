"""Spatial histograms of code maps (the LBPH descriptor core).

Rebuilds the reference's ``SpatialHistogram`` compute kernel (SURVEY.md §2.1
"Feature plugins": grid of LBP histograms, concatenated), TPU-first: instead
of ``np.histogram`` per cell in a Python loop, the code map is cropped to a
multiple of the grid, reshaped into cells, and histogrammed with a one-hot
matmul — one big [pixels, bins] contraction the MXU handles, batched over
leading dims.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def spatial_histogram(
    codes: jnp.ndarray,
    grid: Tuple[int, int] = (8, 8),
    num_bins: int = 256,
    normalize: bool = True,
) -> jnp.ndarray:
    """[..., H, W] int codes -> [..., gy*gx*num_bins] concatenated cell histograms.

    The map is center-cropped so H, W divide evenly by the grid (static
    shapes; the few boundary rows a remainder would cover carry negligible
    signal for LBPH). Each cell histogram is L1-normalized when
    ``normalize`` so the descriptor is comparable across cell sizes.
    """
    codes = jnp.asarray(codes)
    gy, gx = grid
    h, w = codes.shape[-2], codes.shape[-1]
    ch, cw = h // gy, w // gx
    if ch == 0 or cw == 0:
        raise ValueError(f"code map {h}x{w} smaller than grid {grid}")
    # Center crop to (gy*ch, gx*cw).
    y0 = (h - gy * ch) // 2
    x0 = (w - gx * cw) // 2
    codes = codes[..., y0 : y0 + gy * ch, x0 : x0 + gx * cw]
    batch = codes.shape[:-2]
    # [..., gy, ch, gx, cw] -> [..., gy, gx, ch*cw]
    cells = codes.reshape(batch + (gy, ch, gx, cw))
    cells = jnp.swapaxes(cells, -3, -2).reshape(batch + (gy, gx, ch * cw))
    onehot = jax.nn.one_hot(cells, num_bins, dtype=jnp.float32)  # [..., gy, gx, n, B]
    hist = jnp.sum(onehot, axis=-2)  # [..., gy, gx, B]
    if normalize:
        hist = hist / jnp.maximum(jnp.sum(hist, axis=-1, keepdims=True), 1e-12)
    return hist.reshape(batch + (gy * gx * num_bins,))
