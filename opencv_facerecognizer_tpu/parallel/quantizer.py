"""IVF coarse quantizer: the million-identity front end of the two-stage
match path (ROADMAP item #1; the "shortlist + exact rerank" structure of
PAPERS.md's *Fast Matching by 2 Lines of Code for Large Scale Face
Recognition Systems*, arxiv 1302.7180).

The brute-force cosine scan is linear in gallery size — BENCH_r05 measures
1.356 ms/batch at 262k rows and 3.607 ms/batch at 1M (``pallas_stream``),
so 10M identities would blow every serving deadline the runtime protects.
This module prunes the scan: a seeded k-means **coarse quantizer** carves
the gallery into ``nlist`` cells; each cell holds its member rows as an
**int8-quantized, cell-resident inverted list** (contiguous [nlist,
max_cell, D] blocks — a shortlisted cell gathers as one dense block, not
``max_cell`` scattered row reads); matching scores query-vs-centroid,
shortlists the top-``nprobe`` cells, and reranks only their rows with the
existing exact Pallas kernel (``ops.ivf_match`` has the device-side
formulation).

Derived-state contract (the part that must ride the PR-4 lifecycle
untouched — the quantizer is a pure function of the gallery, never a
second source of truth):

- **rebuild** on ``load_snapshot``/startup recovery: ``ShardedGallery``
  invalidates the quantizer on any wholesale state install; recovery
  either restores it from a versioned **sidecar** keyed by the
  checkpoint's ``wal_seq`` (``encode_sidecar``/``decode_sidecar`` —
  written next to the checkpoint, never trusted across a seq mismatch) or
  retrains from the recovered rows. Rebuilds are deterministic: same
  rows + same seed -> bit-identical centroids and assignments on a given
  backend.
- **incremental assignment** on ``ShardedGallery.add``: new rows are
  assigned to their nearest centroid through the same fixed-chunk
  ``assign_rows`` routine the bulk build uses, inserted into their cell's
  list (or the always-scanned **spill** when the cell is full), under the
  gallery's write lock — so WAL replay, which re-drives ``add`` in the
  original order against the sidecar-restored centroids, reproduces the
  exact assignments the live process made.
- **invalidate + rebuild** across ``swap_from``/``reset``: a swapped-in
  gallery shares nothing with the trained cells; serving falls back to
  the exact matcher until a background retrain publishes (mode selection
  lives in ``ShardedGallery.match_fn``).
- **staleness** (spill filling up, or the gallery outgrowing the trained
  row set) triggers a background retrain under the same single-flight
  pattern as the PR-4 checkpointer: one worker at a time, an overlapping
  trigger is counted and dropped, a mid-retrain crash leaves the previous
  published state (or the exact path) serving — never a torn quantizer.

Concurrency: all mutation happens under the owning gallery's write lock
(``ShardedGallery`` calls in from ``add``/``reset``/``load_snapshot``/
``swap_from``, and the retrain worker publishes through
``gallery.run_locked``); readers take the single ``data`` attribute
snapshot, exactly the ``GalleryData`` pattern. The quantizer itself never
acquires the gallery lock while holding any lock of its own — it has
none — so the PR-5 lock-order graph gains only gallery -> Metrics edges.
"""

from __future__ import annotations

import binascii
import hashlib
import json
import logging
import threading
import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.tracing import LIFECYCLE_TOPIC

#: sidecar file magic — identifies the framed quantizer-sidecar format
#: (distinct from the OCVFSTATE gallery checkpoints it rides next to).
SIDECAR_MAGIC = b"OCVFIVF\n"
SIDECAR_FORMAT_VERSION = 1

#: assignment chunk ceiling: rows are scored against centroids in chunks
#: padded to a power-of-two tier (8..ASSIGN_CHUNK), so the compile count
#: is bounded and — the replay contract — a record of n rows re-assigned
#: by WAL replay runs the IDENTICAL compiled shape the live enrolment
#: ran, making the recomputed assignment bit-identical on that backend.
ASSIGN_CHUNK = 8192


class SidecarError(ValueError):
    """The sidecar file is corrupt/truncated or fails its checksum —
    recovery falls back to a full retrain, never a torn quantizer."""


class IVFDeviceData(NamedTuple):
    """One immutable snapshot of the device-visible quantizer state —
    the reader side mirrors ``GalleryData``: a single ``data`` attribute
    load can never observe mixed centroids/lists. All row payloads are
    int8-quantized (per-row scale) so a 10M-row gallery's lists fit HBM
    alongside the exact bf16 rows, and a shortlisted cell streams as one
    dense [max_cell, D] block."""

    centroids: Any      # [nlist, D] f32, L2-normalized
    cell_rows: Any      # [nlist, max_cell] int32 gallery row ids, -1 pad
    cell_q8: Any        # [nlist, max_cell, D] int8 quantized rows
    cell_scale: Any     # [nlist, max_cell] f32 per-row dequant scale
    spill_rows: Any     # [spill_cap] int32 overflow row ids, -1 pad
    spill_q8: Any       # [spill_cap, D] int8
    spill_scale: Any    # [spill_cap] f32
    #: gallery ``_epoch`` at publish: ``ShardedGallery._ivf_data`` rejects
    #: a snapshot whose epoch differs from the paired ``GalleryData``'s,
    #: so two non-atomic reads can never match one row set against
    #: another's lists. (A plain int pytree leaf: jit traces it as a
    #: scalar, so epoch changes never retrace.)
    gallery_epoch: int = 0

    @property
    def nlist(self) -> int:
        return int(self.cell_rows.shape[0])

    @property
    def max_cell(self) -> int:
        return int(self.cell_rows.shape[1])

    @property
    def spill_cap(self) -> int:
        return int(self.spill_rows.shape[0])

    def shape_signature(self) -> Tuple[int, int, int]:
        """The static-shape part of a compiled-matcher cache key: two
        snapshots with equal signatures trace to the same executable."""
        return (self.nlist, self.max_cell, self.spill_cap)


def pack_inverted_lists(ids: np.ndarray, cells: np.ndarray, q8: np.ndarray,
                        scale: np.ndarray, nlist: int,
                        cell_slack: float = 2.0, spill_floor: int = 0):
    """Pure packing of assigned rows into the cell-resident structures:
    ``(cell_rows, cell_q8, cell_scale, spill_rows, spill_q8, spill_scale,
    counts, overflow)``. Rows fill their cell in ascending row-id order;
    rows past ``max_cell`` land in the spill, also ascending — exactly
    the order incremental inserts produce, so a rebuild from a sidecar's
    assignment array reproduces the live structures bit-for-bit. Shared
    by ``CoarseQuantizer`` and the bench ladder (which builds 10M-row
    lists chunk-wise without a host-mirror gallery)."""
    ids = np.asarray(ids, np.int32)
    cells = np.asarray(cells, np.int32)
    q8 = np.asarray(q8, np.int8)
    scale = np.asarray(scale, np.float32)
    n, dim = q8.shape
    mean = max(1.0, n / max(1, nlist))
    max_cell = max(8, int(np.ceil(cell_slack * mean / 8.0) * 8))
    order = np.lexsort((ids, cells))
    s_ids, s_cells = ids[order], cells[order]
    counts = np.bincount(s_cells, minlength=nlist).astype(np.int64)
    starts = np.zeros(nlist + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(n, dtype=np.int64) - starts[s_cells]
    in_cell = pos < max_cell
    overflow = int(n - in_cell.sum())
    spill_cap = int(np.ceil((max(overflow, spill_floor) + 256) / 256.0) * 256)
    cell_rows = np.full((nlist, max_cell), -1, np.int32)
    cell_q8 = np.zeros((nlist, max_cell, dim), np.int8)
    cell_scale = np.zeros((nlist, max_cell), np.float32)
    cr, cp = s_cells[in_cell], pos[in_cell]
    cell_rows[cr, cp] = s_ids[in_cell]
    cell_q8[cr, cp] = q8[order][in_cell]
    cell_scale[cr, cp] = scale[order][in_cell]
    spill_rows = np.full((spill_cap,), -1, np.int32)
    spill_q8 = np.zeros((spill_cap, dim), np.int8)
    spill_scale = np.zeros((spill_cap,), np.float32)
    if overflow:
        sp_order = np.argsort(s_ids[~in_cell])
        spill_rows[:overflow] = s_ids[~in_cell][sp_order]
        spill_q8[:overflow] = q8[order][~in_cell][sp_order]
        spill_scale[:overflow] = scale[order][~in_cell][sp_order]
    counts_clamped = np.minimum(counts, max_cell).astype(np.int32)
    return (cell_rows, cell_q8, cell_scale, spill_rows, spill_q8,
            spill_scale, counts_clamped, overflow)


def quantize_rows(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row symmetric int8 quantization: ``row ~= q8 * scale``.

    For L2-normalized embeddings the max |component| is ~0.2 at D=256, so
    the per-component step (scale ~ max/127) puts the dot-product error
    well under the bf16 rounding the exact kernel already accepts — the
    recall gate in tests measures the end-to-end effect.
    """
    rows = np.asarray(rows, np.float32)
    scale = np.max(np.abs(rows), axis=-1) / 127.0
    scale = np.maximum(scale, np.float32(1e-12)).astype(np.float32)
    q8 = np.clip(np.rint(rows / scale[..., None]), -127, 127).astype(np.int8)
    return q8, scale


def _kmeans(rows: np.ndarray, nlist: int, iters: int, seed: int) -> np.ndarray:
    """Seeded spherical k-means on the device (jax): centroids stay
    L2-normalized so centroid score == expected member cosine. Empty
    cells keep their previous centroid (deterministic; they simply stop
    attracting rows). Same rows + seed -> bit-identical centroids on a
    given backend."""
    import jax
    import jax.numpy as jnp

    rows = np.asarray(rows, np.float32)
    s = rows.shape[0]
    key = jax.random.PRNGKey(int(seed))
    perm = np.asarray(jax.random.permutation(key, s))
    init = rows[perm[np.arange(nlist) % s]]

    @jax.jit
    def step(x, c):
        sims = x @ c.T  # f32: determinism beats MXU speed at train size
        assign = jnp.argmax(sims, axis=1)
        ones = jnp.ones((x.shape[0],), jnp.float32)
        counts = jax.ops.segment_sum(ones, assign, num_segments=nlist)
        sums = jax.ops.segment_sum(x, assign, num_segments=nlist)
        mean = sums / jnp.maximum(counts, 1.0)[:, None]
        norm = jnp.linalg.norm(mean, axis=1, keepdims=True)
        newc = mean / jnp.maximum(norm, 1e-12)
        return jnp.where((counts > 0)[:, None], newc, c)

    c = jnp.asarray(init)
    x = jnp.asarray(rows)
    for _ in range(max(1, int(iters))):
        c = step(x, c)
    return np.asarray(c, np.float32)


class CoarseQuantizer:
    """Seeded k-means coarse quantizer over a ``ShardedGallery``'s rows,
    with int8 cell-resident inverted lists and an always-exact spill.

    Attach with ``gallery.attach_quantizer(quantizer, mode=...)``; the
    gallery then drives every lifecycle edge (see module docstring). The
    matcher side is ``ops.ivf_match.ivf_match_topk`` over ``self.data``.
    """

    #: spill high-water fraction that marks the quantizer stale — the
    #: spill is scanned exactly on every match, so a full spill is a
    #: perf (never a recall) problem.
    SPILL_STALE_FRACTION = 0.75

    #: gallery growth past the trained row set that marks it stale:
    #: centroids trained on 1/GROWTH_STALE_FACTOR of the rows no longer
    #: describe the distribution.
    GROWTH_STALE_FACTOR = 1.5

    #: per-cell slack over the perfectly balanced size; rows past it spill.
    CELL_SLACK = 2.0

    def __init__(self, nlist: int = 1024, nprobe: int = 8, seed: int = 0,
                 kmeans_iters: int = 10, train_sample: int = 131072,
                 metrics=None, auto_nlist: bool = False):
        #: with ``auto_nlist`` the cell count re-derives from the ACTUAL
        #: row count at every rebuild (and adopts the sidecar's on
        #: recovery) — a startup guess from ``capacity`` would otherwise
        #: freeze a too-small nlist across recovery of a much larger
        #: checkpoint or 10x runtime growth, quietly bloating every
        #: rerank bucket.
        self.auto_nlist = bool(auto_nlist)
        self.nlist = int(nlist)
        self.nprobe = int(nprobe)
        self.seed = int(seed)
        self.kmeans_iters = int(kmeans_iters)
        self.train_sample = int(train_sample)
        self.metrics = metrics
        #: optional utils.tracing.Tracer — one lifecycle span per retrain
        #: attempt (outcome ok/skipped/failed), set alongside ``metrics``
        #: by the serving app. Never touched on the match hot path.
        self.tracer = None
        self._gallery = None  # set by ShardedGallery.attach_quantizer
        #: single published device snapshot (None == not ready; serving
        #: falls back to the exact matcher).
        self._data: Optional[IVFDeviceData] = None
        self.version = 0
        self.trained_size = 0
        #: host mirrors, mutated only under the gallery write lock.
        self._h_centroids: Optional[np.ndarray] = None
        self._h_assign = np.zeros((0,), np.int32)  # [capacity] cell or -1
        self._h_counts: Optional[np.ndarray] = None  # [nlist] rows per cell
        self._spill_count = 0
        self._assigned_rows = 0  # row-id high-water covered by the lists
        # Single-flight retrain guard — the PR-4 checkpoint pattern: one
        # background worker at a time; an overlapping trigger is counted
        # and dropped (staleness re-fires on the next add).
        self._train_lock = threading.Lock()
        #: set when a build was fenced out by an epoch bump (swap/load/
        #: reset landed mid-train): rebuild_now re-fires one async build
        #: after releasing the guard, because the invalidation's own poke
        #: was skipped as in-flight — without the re-fire a match-heavy,
        #: no-further-enrolment workload would stay pinned to the exact
        #: scan forever.
        self._fence_refire = False
        self._assign_jit = None
        self._scatter_jit = None
        #: device copy of ``_h_centroids``, lazily re-put after each
        #: (re)build/invalidate — assignment must not re-upload the
        #: [nlist, D] matrix on every enrolment.
        self._c_dev = None

    @staticmethod
    def default_nlist(rows: int) -> int:
        """~4*sqrt(rows) rounded to a power of two, clamped to [64,
        16384] — the classic IVF sizing: cells of ~sqrt(rows)/4 rows keep
        the stage-1 scan and the stage-2 buckets balanced as the gallery
        scales 262k -> 10M."""
        target = 4.0 * np.sqrt(max(1, int(rows)))
        nlist = 64
        while nlist < target and nlist < 16384:
            nlist *= 2
        return nlist

    # ---- read side ----

    @property
    def ready(self) -> bool:
        return self._data is not None

    @property
    def data(self) -> Optional[IVFDeviceData]:
        return self._data

    @property
    def spill_count(self) -> int:
        return self._spill_count

    def stats(self) -> Dict[str, Any]:
        data = self._data
        return {
            "ready": data is not None,
            "version": self.version,
            "nlist": self.nlist,
            "nprobe": self.nprobe,
            "trained_size": self.trained_size,
            "assigned_rows": self._assigned_rows,
            "spill_count": self._spill_count,
            "spill_cap": 0 if data is None else data.spill_cap,
            "max_cell": 0 if data is None else data.max_cell,
        }

    # ---- assignment (the ONE routine every path shares) ----

    @staticmethod
    def _pad_tier(n: int) -> int:
        """Power-of-two pad tier for a chunk of ``n`` rows: bounds the
        compile count while keeping each record's replay on the exact
        compiled shape its live enrolment used."""
        tier = 8
        while tier < n:
            tier *= 2
        return min(tier, ASSIGN_CHUNK)

    def assign_rows(self, rows: np.ndarray,
                    centroids: Optional[np.ndarray] = None) -> np.ndarray:
        """Nearest-centroid cell ids for L2-normalized rows — the ONE
        assignment routine shared by bulk build, incremental enrolment
        and WAL replay, chunked to fixed pad tiers (``_pad_tier``) so a
        replayed record recomputes bit-identical assignments on the same
        backend. Ties break to the lowest cell id (argmax-first),
        matching the stage-1 shortlist's ``top_k`` order."""
        import jax
        import jax.numpy as jnp

        if centroids is None:
            centroids = self._h_centroids
        if centroids is None:
            raise RuntimeError("quantizer has no centroids: build first")
        rows = np.asarray(rows, np.float32)
        n = rows.shape[0]
        if self._assign_jit is None:
            self._assign_jit = jax.jit(
                lambda x, c: jnp.argmax(x @ c.T, axis=1).astype(jnp.int32))
        if centroids is self._h_centroids:
            if self._c_dev is None:
                self._c_dev = jnp.asarray(centroids)
            c_dev = self._c_dev
        else:
            c_dev = jnp.asarray(centroids)
        out = np.empty((n,), np.int32)
        for off in range(0, n, ASSIGN_CHUNK):
            chunk = rows[off:off + ASSIGN_CHUNK]
            got_n = chunk.shape[0]
            pad = self._pad_tier(got_n) - got_n
            if pad:
                chunk = np.pad(chunk, ((0, pad), (0, 0)))
            got = np.asarray(self._assign_jit(jnp.asarray(chunk), c_dev))
            out[off:off + got_n] = got[:got_n]
        return out

    # ---- building (bulk) ----

    def _pack(self, emb: np.ndarray, val: np.ndarray, assign: np.ndarray,
              spill_floor: int = 0):
        """Quantize the valid rows and pack them through the shared
        ``pack_inverted_lists`` routine (module docstring has the
        ordering contract)."""
        ids = np.nonzero(val)[0].astype(np.int32)
        q8, scale = quantize_rows(emb[ids])
        return pack_inverted_lists(ids, assign[ids], q8, scale, self.nlist,
                                   cell_slack=self.CELL_SLACK,
                                   spill_floor=spill_floor)

    def _device_put(self, centroids, cell_rows, cell_q8, cell_scale,
                    spill_rows, spill_q8, spill_scale) -> IVFDeviceData:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from opencv_facerecognizer_tpu.parallel.mesh import TP_AXIS

        mesh = self._gallery.mesh
        rep = NamedSharding(mesh, P())
        # Cell-resident arrays shard over cells like the gallery shards
        # over rows; on the single-device meshes the ivf path is gated to,
        # this is placement only.
        by_cell = (NamedSharding(mesh, P(TP_AXIS, None))
                   if cell_rows.shape[0] % mesh.shape[TP_AXIS] == 0 else rep)
        by_cell3 = (NamedSharding(mesh, P(TP_AXIS, None, None))
                    if cell_rows.shape[0] % mesh.shape[TP_AXIS] == 0 else rep)
        return IVFDeviceData(
            centroids=jax.device_put(jnp.asarray(centroids), rep),
            cell_rows=jax.device_put(jnp.asarray(cell_rows), by_cell),
            cell_q8=jax.device_put(jnp.asarray(cell_q8), by_cell3),
            cell_scale=jax.device_put(jnp.asarray(cell_scale), by_cell),
            spill_rows=jax.device_put(jnp.asarray(spill_rows), rep),
            spill_q8=jax.device_put(jnp.asarray(spill_q8), rep),
            spill_scale=jax.device_put(jnp.asarray(spill_scale), rep),
        )

    def rebuild_now(self, wait: bool = True,
                    skip_if_ready: bool = False) -> bool:
        """One full retrain: snapshot the gallery, train seeded k-means on
        a row subsample, assign every row, pack + upload, publish under
        the gallery write lock with a catch-up pass for rows enrolled
        since the snapshot. Returns False when another retrain holds the
        single-flight guard (and ``wait`` is False) or the build failed
        (counted ``ivf_build_failures``; previous state keeps serving).
        ``skip_if_ready`` turns the call into "ensure built": with
        ``wait`` it first rides out any in-flight background build and
        returns True without retraining when that build (or an earlier
        one) already published — the startup path uses this so a
        recovery-poked background build is never duplicated."""
        if self._gallery is None:
            raise RuntimeError("quantizer not attached to a gallery")
        if not self._train_lock.acquire(blocking=wait):
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_RETRAINS_SKIPPED_INFLIGHT)
            return False
        span_t0 = time.monotonic()
        outcome = "failed"
        try:
            if skip_if_ready and self._data is not None:
                outcome = "already_ready"
                return True
            ok = self._rebuild_locked()
            outcome = "ok" if ok else "fenced"
            return ok
        except Exception:  # noqa: BLE001 — a failed retrain must leave the
            # previous quantizer (or the exact path) serving, never crash
            # an enroll/serving thread that triggered it.
            logging.getLogger(__name__).exception("ivf rebuild failed")
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_BUILD_FAILURES)
            return False
        finally:
            self._train_lock.release()
            if self.tracer is not None:
                # One lifecycle span per retrain attempt, emitted after
                # the single-flight guard is released.
                self.tracer.emit(
                    self.tracer.new_trace(), "ivf_retrain",
                    topic=LIFECYCLE_TOPIC, t0=span_t0,
                    dur=time.monotonic() - span_t0, outcome=outcome,
                    nlist=self.nlist, version=self.version)
            if self._fence_refire:
                # The epoch fence discarded this build (a swap/load/reset
                # landed mid-train) AND that invalidation's poke was
                # skipped as in-flight: fire one fresh attempt against
                # the new row set. Only fences re-fire — failures must
                # not storm — and maybe_rebuild_async single-flights.
                self._fence_refire = False
                g = self._gallery
                if (g is not None and self._data is None
                        and g._ivf_wanted() and g.size > 0):
                    self.maybe_rebuild_async()

    def _rebuild_locked(self) -> bool:
        t0 = time.perf_counter()
        g = self._gallery
        # Epoch fence: a reset/swap_from/load_snapshot during this build
        # invalidates it — publishing centroids trained on the PREVIOUS
        # row set over a swapped-in gallery would be silently wrong.
        epoch0 = g.run_locked(lambda: g._epoch)
        emb, _lab, val, _size = g.snapshot()
        n_valid = int(val.sum())
        if n_valid < max(2, min(self.nlist, 8)):
            return False  # nothing meaningful to train on
        if self.auto_nlist:
            self.nlist = self.default_nlist(n_valid)
        ids = np.nonzero(val)[0]
        rows = emb[ids]
        sample = rows
        if len(rows) > self.train_sample:
            rng = np.random.default_rng(self.seed)
            pick = np.sort(rng.choice(len(rows), self.train_sample,
                                      replace=False))
            sample = rows[pick]
        centroids = _kmeans(sample, self.nlist, self.kmeans_iters, self.seed)
        assign_valid = self.assign_rows(rows, centroids)
        assign = np.full((emb.shape[0],), -1, np.int32)
        assign[ids] = assign_valid
        packed = self._pack(emb, val, assign)
        (cell_rows, cell_q8, cell_scale, spill_rows, spill_q8, spill_scale,
         counts, overflow) = packed
        data = self._device_put(centroids, cell_rows, cell_q8, cell_scale,
                                spill_rows, spill_q8, spill_scale)
        published = []

        def publish():
            if g._epoch != epoch0:
                return  # superseded: the invalidation wins, like a grow
            # Under the gallery write lock: no add can interleave, so the
            # catch-up below sees a settled row set.
            self._h_centroids = centroids
            self._c_dev = None  # lazily re-put on the next assignment
            self._h_assign = assign
            self._h_counts = counts
            self._spill_count = overflow
            self._assigned_rows = int(ids[-1]) + 1 if len(ids) else 0
            self.trained_size = n_valid
            self._data = data._replace(gallery_epoch=g._epoch)
            self.version += 1
            published.append(True)
            # Catch-up: rows enrolled between the snapshot above and this
            # publish are re-assigned against the NEW centroids and
            # inserted exactly like any incremental add. Valid rows are
            # a prefix (append-only within an epoch), so the tail is one
            # contiguous range — ONE batched insert, not a per-row loop
            # of full-array device copies under the write lock.
            tail = g._host_val.copy()
            tail[:emb.shape[0]] &= ~val[:len(tail)][:emb.shape[0]]
            tail_ids = np.nonzero(tail)[0]
            if len(tail_ids):
                lo, hi = int(tail_ids[0]), int(tail_ids[-1]) + 1
                if hi - lo == len(tail_ids):
                    self.on_rows_added(g._host_emb[lo:hi], lo)
                else:  # non-contiguous (defensive): per-row fallback
                    for rid in tail_ids:
                        self.on_rows_added(g._host_emb[rid][None, :],
                                           int(rid))

        g.run_locked(publish)
        if not published:
            self._fence_refire = True  # retry against the new row set
            return False
        if self.metrics is not None:
            self.metrics.incr(mn.IVF_BUILDS)
            self.metrics.set_gauge(mn.IVF_SPILL_ROWS, self._spill_count)
        logging.getLogger(__name__).info(
            "ivf rebuild v%d: %d rows, nlist=%d, max_cell=%d, spill=%d "
            "(%.2fs)", self.version, n_valid, self.nlist,
            cell_rows.shape[1], overflow, time.perf_counter() - t0)
        return True

    def maybe_rebuild_async(self) -> bool:
        """Spawn a background retrain unless one is already in flight
        (single-flight, like ``StateLifecycle.maybe_checkpoint``)."""
        if self._gallery is None:
            return False
        if self._train_lock.locked():
            if self.metrics is not None:
                self.metrics.incr(mn.IVF_RETRAINS_SKIPPED_INFLIGHT)
            return False
        threading.Thread(target=self.rebuild_now, kwargs={"wait": False},
                         daemon=True, name="ivf-retrain").start()
        return True

    # ---- lifecycle edges driven by the gallery ----

    def invalidate(self) -> None:
        """Drop the published state: called (under the gallery write lock)
        on ``reset``/``load_snapshot``/``swap_from`` and on an async-grow
        splice — wholesale row-set changes the cells know nothing about.
        Serving falls back to the exact matcher until a rebuild lands."""
        self._data = None
        self._h_centroids = None
        self._c_dev = None
        self._h_assign = np.zeros((0,), np.int32)
        self._h_counts = None
        self._spill_count = 0
        self._assigned_rows = 0
        self.trained_size = 0
        if self.metrics is not None:
            self.metrics.incr(mn.IVF_INVALIDATIONS)

    def stale(self) -> bool:
        """Cheap staleness check (called outside locks after an add)."""
        data = self._data
        if data is None:
            return False
        if self._spill_count >= self.SPILL_STALE_FRACTION * data.spill_cap:
            return True
        size = self._gallery.size if self._gallery is not None else 0
        return size > self.GROWTH_STALE_FACTOR * max(1, self.trained_size)

    def on_rows_added(self, rows: np.ndarray, start: int) -> None:
        """Incrementally assign freshly enrolled rows (called by
        ``ShardedGallery.add`` under its write lock, AFTER the host
        mirrors hold the rows). ``rows`` are the L2-normalized embeddings;
        row ids are ``start..start+n``. No-op while not ready — the next
        rebuild covers everything.

        Batched: ONE assignment dispatch and one scatter per structure
        (cell side + spill side) per ``ASSIGN_CHUNK`` rows — a per-row
        loop would copy the whole [nlist, max_cell, D] arrays n times
        while holding the gallery write lock, and an unchunked scatter
        would blow the pad-tier cap on a huge WAL-replay record. A row
        that fits neither its cell nor the spill invalidates the
        quantizer (recall must never silently drop a row); the partially
        updated snapshot is never published."""
        if self._data is None:
            return
        rows = np.asarray(rows, np.float32)
        for off in range(0, rows.shape[0], ASSIGN_CHUNK):
            if not self._add_rows_chunk(rows[off:off + ASSIGN_CHUNK],
                                        start + off):
                return  # invalidated: the remaining rows are moot

    def _add_rows_chunk(self, rows: np.ndarray, start: int) -> bool:
        """One <= ASSIGN_CHUNK slice of ``on_rows_added``; False when the
        structures overflowed (or an insert failed) and the quantizer
        invalidated itself. Fail-closed: a scatter that dies mid-chunk
        (transient device error) would leave the host counts claiming
        placements the published lists never got — invalidate instead of
        crashing the enroll thread, exactly the rebuild failure
        contract."""
        try:
            return self._add_rows_chunk_inner(rows, start)
        except Exception:  # noqa: BLE001 — enroll threads must never die
            # to derived-state bookkeeping; exact serving continues.
            logging.getLogger(__name__).exception(
                "ivf incremental insert failed; invalidating")
            self.invalidate()
            return False

    def _add_rows_chunk_inner(self, rows: np.ndarray, start: int) -> bool:
        data = self._data
        if data is None:
            return False
        n = rows.shape[0]
        if not n:
            return True
        cells = self.assign_rows(rows)
        q8, scale = quantize_rows(rows)
        self._grow_assign(start + n - 1)
        c_sel, c_cell, c_pos = [], [], []
        s_sel, s_pos = [], []
        for i in range(n):
            cell = int(cells[i])
            self._h_assign[start + i] = cell
            count = int(self._h_counts[cell])
            if count < data.max_cell:
                c_sel.append(i)
                c_cell.append(cell)
                c_pos.append(count)
                self._h_counts[cell] = count + 1
            elif self._spill_count < data.spill_cap:
                s_sel.append(i)
                s_pos.append(self._spill_count)
                self._spill_count += 1
            else:
                # Cell AND spill full: the structures cannot hold the
                # row; fall back to exact serving until the retrain the
                # caller's staleness poke fires republishes.
                self.invalidate()
                return False
        rids = np.arange(start, start + n, dtype=np.int32)
        cell_sc, spill_sc = self._scatter_jits()
        if c_sel:
            tier = self._pad_tier(len(c_sel))
            c, p, r, qq, ss = self._pad_batch(
                (np.asarray(c_cell, np.int32), np.asarray(c_pos, np.int32),
                 rids[c_sel], q8[c_sel], scale[c_sel]), tier)
            cr, cq, cs = cell_sc(data.cell_rows, data.cell_q8,
                                 data.cell_scale, c, p, r, qq, ss)
            data = data._replace(cell_rows=cr, cell_q8=cq, cell_scale=cs)
        if s_sel:
            tier = self._pad_tier(len(s_sel))
            p, r, qq, ss = self._pad_batch(
                (np.asarray(s_pos, np.int32), rids[s_sel], q8[s_sel],
                 scale[s_sel]), tier)
            sr, sq, sscale = spill_sc(data.spill_rows, data.spill_q8,
                                      data.spill_scale, p, r, qq, ss)
            data = data._replace(spill_rows=sr, spill_q8=sq,
                                 spill_scale=sscale)
        self._data = data
        self._assigned_rows = max(self._assigned_rows, start + n)
        if self.metrics is not None:
            self.metrics.incr(mn.IVF_INCREMENTAL_ROWS, n)
            self.metrics.set_gauge(mn.IVF_SPILL_ROWS, self._spill_count)
        return True

    def _grow_assign(self, max_rid: int) -> None:
        if max_rid < len(self._h_assign):
            return
        grown = np.full((max(max_rid + 1, 2 * max(1, len(self._h_assign))),),
                        -1, np.int32)
        grown[:len(self._h_assign)] = self._h_assign
        self._h_assign = grown

    @staticmethod
    def _pad_batch(arrays, tier: int):
        """Pad scatter operands to the tier by repeating the LAST entry:
        duplicate scatter indices then write the same value, so the pad
        is idempotent and the compile count stays bounded."""
        out = []
        for a in arrays:
            pad = tier - len(a)
            out.append(np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
                       if pad else np.asarray(a))
        return out

    def _scatter_jits(self):
        import jax

        if self._scatter_jit is None:
            def cell_sc(cr, cq, cs, c, p, rid, q8rows, sc):
                return (cr.at[c, p].set(rid), cq.at[c, p].set(q8rows),
                        cs.at[c, p].set(sc))

            def spill_sc(sr, sq, ss, p, rid, q8rows, sc):
                return (sr.at[p].set(rid), sq.at[p].set(q8rows),
                        ss.at[p].set(sc))

            # No donation: in-flight matchers still read the old arrays;
            # the .at copy is device-bandwidth cheap and enrolment-rate.
            self._scatter_jit = (jax.jit(cell_sc), jax.jit(spill_sc))
        return self._scatter_jit

    # ---- sidecar (derived-state persistence keyed by checkpoint) ----

    def sidecar_payload_locked(self) -> Optional[Dict[str, Any]]:
        """Host-copy capture for the sidecar writer — called by
        ``ShardedGallery.snapshot_quantizer`` under the gallery write
        lock, so it pairs atomically with the gallery snapshot taken in
        the same checkpoint critical section."""
        if self._data is None or self._h_centroids is None:
            return None
        return {
            "centroids": self._h_centroids.copy(),
            "assign": self._h_assign.copy(),
            "nlist": self.nlist,
            "seed": self.seed,
            "trained_size": self.trained_size,
            "spill_count": self._spill_count,
            "version": self.version,
            # Embedder version the centroids were trained in: derived
            # state is space-bound — a sidecar surviving a rollout
            # cutover must fail closed to a retrain (state_store checks
            # this on restore; wal_seq keying covers the common case,
            # this is the defense-in-depth).
            "embedder_version": int(getattr(self._gallery,
                                            "embedder_version", 1)),
        }

    def install_from_arrays(self, centroids: np.ndarray,
                            assign: np.ndarray) -> bool:
        """Rebuild the packed structures from a sidecar's (centroids,
        assignment) against the gallery's CURRENT host mirrors — pure
        repack, no k-means — and publish. The pack routine is the same
        one live builds use, so the result is bit-identical to the state
        the sidecar captured."""
        if self._gallery is None:
            raise RuntimeError("quantizer not attached to a gallery")
        g = self._gallery
        emb, _lab, val, _size = g.snapshot()
        centroids = np.asarray(centroids, np.float32)
        if self.auto_nlist:
            # Auto-sized quantizers adopt the sidecar's cell count — the
            # startup guess from ``capacity`` may not match the recovered
            # row set's sizing (and a mismatch here is config drift only
            # when nlist was pinned explicitly).
            self.nlist = int(centroids.shape[0])
        elif int(centroids.shape[0]) != self.nlist:
            return False  # pinned nlist disagrees with the sidecar
        assign_full = np.full((emb.shape[0],), -1, np.int32)
        n = min(len(assign), emb.shape[0])
        assign_full[:n] = assign[:n]
        assign_full[~val] = -1
        if np.any(val & (assign_full < 0)):
            return False  # sidecar does not cover every live row
        packed = self._pack(emb, val, assign_full)
        (cell_rows, cell_q8, cell_scale, spill_rows, spill_q8, spill_scale,
         counts, overflow) = packed
        data = self._device_put(centroids, cell_rows, cell_q8, cell_scale,
                                spill_rows, spill_q8, spill_scale)
        ids = np.nonzero(val)[0]

        def publish():
            self._h_centroids = centroids
            self._c_dev = None
            self._h_assign = assign_full
            self._h_counts = counts
            self._spill_count = overflow
            self._assigned_rows = int(ids[-1]) + 1 if len(ids) else 0
            self.trained_size = int(val.sum())
            self._data = data._replace(gallery_epoch=g._epoch)
            self.version += 1

        g.run_locked(publish)
        return True


def encode_sidecar(payload: Dict[str, Any], wal_seq: int) -> bytes:
    """``MAGIC + u32 header_len + header_json + sha256(header) + body``
    where the body is the raw centroid f32 bytes then the assignment
    int32 bytes, each crc32'd in the header — the same framing discipline
    as the PR-4 checkpoints, because the sidecar makes the same promise:
    a torn write must fail closed (retrain), never half-load."""
    cent = np.ascontiguousarray(payload["centroids"], np.float32)
    assign = np.ascontiguousarray(payload["assign"], np.int32)
    cent_b, assign_b = cent.tobytes(), assign.tobytes()
    header = {
        "format_version": SIDECAR_FORMAT_VERSION,
        "wal_seq": int(wal_seq),
        "nlist": int(payload["nlist"]),
        "dim": int(cent.shape[1]),
        "rows": int(assign.shape[0]),
        "seed": int(payload["seed"]),
        "trained_size": int(payload["trained_size"]),
        "version": int(payload["version"]),
        "embedder_version": int(payload.get("embedder_version", 1)),
        "crc32_centroids": binascii.crc32(cent_b) & 0xFFFFFFFF,
        "crc32_assign": binascii.crc32(assign_b) & 0xFFFFFFFF,
        "created_ts": time.time(),
    }
    blob = json.dumps(header, sort_keys=True).encode("utf-8")
    return (SIDECAR_MAGIC + len(blob).to_bytes(4, "big") + blob
            + hashlib.sha256(blob).digest() + cent_b + assign_b)


def decode_sidecar(blob: bytes) -> Tuple[Dict[str, Any], np.ndarray,
                                         np.ndarray]:
    """Parse + validate sidecar bytes -> (header, centroids, assign);
    raises ``SidecarError`` on any framing/checksum miss."""
    if not blob.startswith(SIDECAR_MAGIC):
        raise SidecarError("bad sidecar magic")
    off = len(SIDECAR_MAGIC)
    if len(blob) < off + 4:
        raise SidecarError("truncated before header")
    hlen = int.from_bytes(blob[off:off + 4], "big")
    off += 4
    if hlen <= 0 or len(blob) < off + hlen + 32:
        raise SidecarError("truncated header")
    header_blob = blob[off:off + hlen]
    if hashlib.sha256(header_blob).digest() != blob[off + hlen:off + hlen + 32]:
        raise SidecarError("header sha256 mismatch")
    try:
        header = json.loads(header_blob.decode("utf-8"))
        version = int(header["format_version"])
        nlist, dim, rows = (int(header["nlist"]), int(header["dim"]),
                            int(header["rows"]))
    except (UnicodeDecodeError, json.JSONDecodeError, KeyError, TypeError,
            ValueError) as exc:
        raise SidecarError(f"header decode failed: {exc!r}") from exc
    if version > SIDECAR_FORMAT_VERSION:
        raise SidecarError(f"sidecar format v{version} newer than supported")
    body = blob[off + hlen + 32:]
    cent_bytes = nlist * dim * 4
    if len(body) != cent_bytes + rows * 4:
        raise SidecarError("payload truncated")
    cent_b, assign_b = body[:cent_bytes], body[cent_bytes:]
    if (binascii.crc32(cent_b) & 0xFFFFFFFF) != header["crc32_centroids"]:
        raise SidecarError("centroid crc32 mismatch")
    if (binascii.crc32(assign_b) & 0xFFFFFFFF) != header["crc32_assign"]:
        raise SidecarError("assignment crc32 mismatch")
    centroids = np.frombuffer(cent_b, np.float32).reshape(nlist, dim).copy()
    assign = np.frombuffer(assign_b, np.int32).copy()
    return header, centroids, assign
