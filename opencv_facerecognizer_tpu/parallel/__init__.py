"""Device-mesh parallelism (SURVEY.md §2.3).

The reference is single-process NumPy — its only distribution is host-level
pub-sub. The rebuild makes two parallel axes first-class, per the north star
(BASELINE.json:5):

- ``dp`` — data parallel: frame/face batches sharded across chips.
- ``tp`` — tensor parallel: the enrolled-gallery embedding matrix sharded
  across chips' HBM; similarity matmul per shard + cross-device top-k merge.

Collectives ride ICI via ``shard_map`` + ``all_gather``/``psum``; the
host-level application transport stays a separate layer (``runtime``).
"""

from opencv_facerecognizer_tpu.parallel.mesh import initialize_multihost, make_mesh
from opencv_facerecognizer_tpu.parallel.gallery import (
    EmbeddingDimMismatchError,
    ShardedGallery,
)

__all__ = ["CoarseQuantizer", "EmbeddingDimMismatchError", "ShardedGallery",
           "TwoStagePipeline", "initialize_multihost", "make_mesh",
           "split_mesh"]


def __getattr__(name):
    # pp pulls the full flax model stack; keep `parallel` import light for
    # mesh/gallery-only consumers (enrolment tooling, multi-host bootstrap).
    # quantizer is lazy for the same reason (it imports jax at build time).
    if name in ("TwoStagePipeline", "split_mesh"):
        from opencv_facerecognizer_tpu.parallel import pp

        return getattr(pp, name)
    if name == "CoarseQuantizer":
        from opencv_facerecognizer_tpu.parallel.quantizer import CoarseQuantizer

        return CoarseQuantizer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
