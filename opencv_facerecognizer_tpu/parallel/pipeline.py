"""The fused serving graph: detect -> align -> embed -> match as ONE jitted,
mesh-sharded call per frame batch (BASELINE.json:5: "detect->align->embed->
match executes as one pmap'd call per batch"; SURVEY.md §3.3 rebuild note).

Static-shape discipline end-to-end (SURVEY.md §7 "hard parts"): every frame
contributes exactly ``max_faces`` slots; empty slots ride along as invalid
(masked) work. TPUs vastly prefer predictable dense compute over dynamic
shapes — invalid-slot embeddings are garbage lanes of a batched matmul, not
wasted recompiles.

Sharding: frames are dp-sharded; detector/embedder params are replicated;
the gallery match inside is tp-sharded (see ``parallel.gallery``). XLA
inserts the collectives; nothing here names a wire protocol.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from opencv_facerecognizer_tpu.models import detector as detector_mod
from opencv_facerecognizer_tpu.models import embedder as embedder_mod
from opencv_facerecognizer_tpu.ops import image as image_ops
from opencv_facerecognizer_tpu.parallel.gallery import ShardedGallery
from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS


class RecognitionResult(NamedTuple):
    boxes: jnp.ndarray  # [B, K, 4] pixel yxyx
    det_scores: jnp.ndarray  # [B, K]
    valid: jnp.ndarray  # [B, K] bool
    labels: jnp.ndarray  # [B, K, k] gallery labels, best first
    similarities: jnp.ndarray  # [B, K, k] cosine similarity


def pack_result(result: "RecognitionResult") -> jnp.ndarray:
    """[B, K, 6 + 2k] f32: boxes | det_score | valid | labels | sims.

    One output array instead of five: on a tunneled backend every blocking
    device->host readback pays a ~100 ms sync-poll floor (measured: 5
    separate readbacks 503 ms/batch, 1 packed readback 105 ms/batch), so
    the serving loop reads back exactly one array per batch. Labels ride
    as f32 (exact for values < 2^24 — far beyond any gallery capacity).
    """
    return jnp.concatenate([
        result.boxes,
        result.det_scores[..., None],
        result.valid[..., None].astype(jnp.float32),
        result.labels.astype(jnp.float32),
        result.similarities,
    ], axis=-1)


def unpack_result(packed: np.ndarray, top_k: int) -> RecognitionResult:
    """Host-side inverse of ``pack_result`` (numpy views, no copies)."""
    return RecognitionResult(
        boxes=packed[..., 0:4],
        det_scores=packed[..., 4],
        valid=packed[..., 5] > 0.5,
        labels=packed[..., 6:6 + top_k].astype(np.int32),
        similarities=packed[..., 6 + top_k:6 + 2 * top_k],
    )


class RecognitionPipeline:
    """Holds the nets + gallery and compiles the fused per-batch step."""

    def __init__(
        self,
        detector: detector_mod.CNNFaceDetector,
        embed_net: embedder_mod.FaceEmbedNet,
        embed_params: Dict[str, Any],
        gallery: ShardedGallery,
        face_size: Tuple[int, int] = (112, 112),
        top_k: int = 1,
        fused_embedder: bool = False,
        donate_frames: bool = False,
        cascade=None,
    ):
        self.detector = detector
        self.embed_net = embed_net
        self.embed_params = embed_params
        self.gallery = gallery
        self.face_size = tuple(face_size)
        self.top_k = int(top_k)
        # Stage-1 detection cascade (models.cascade.FaceGate): when set,
        # the serving runtime scores every batch with ``cascade_scores``
        # first and only survivors reach the fused detect->crop->embed->
        # match step — rejected frames settle as ``completed_empty`` in
        # the admission ledger (runtime/recognizer.py owns the decision;
        # this object only holds the compiled per-rung stage-1 pass).
        self.cascade = cascade
        # Donate the frames argument of the PACKED serving step through
        # the whole bucketed ladder: the ingest uploader ships each batch
        # as its own fresh device array (uint8, one device_put per
        # dispatch attempt), so XLA may reuse that buffer's memory for
        # outputs instead of allocating. Only flip this on backends that
        # implement input donation (TPU/GPU — CPU ignores it with a
        # warning) AND when every caller routes through the uploader:
        # a donated array must never be re-fed after dispatch.
        self.donate_frames = bool(donate_frames)
        # Opt-in pallas schedule for the embed stage (ops.pallas_sepblock;
        # same params/math, equivalence pinned in tests). Stays off by
        # default until scripts/bench_sepblock.py measures a win on chip —
        # the flip is then this one flag. Single-device meshes only: GSPMD
        # cannot partition a pallas custom call over the mesh, so fail
        # fast here instead of dying in an opaque Mosaic partition error
        # at first dispatch.
        if fused_embedder and gallery.mesh.size > 1:
            raise ValueError(
                "fused_embedder=True requires a single-device mesh "
                f"(got {gallery.mesh.size} devices)")
        self.fused_embedder = bool(fused_embedder)
        # Chaos hook (runtime.faults.FaultInjector): checked at the device-
        # dispatch boundary of both recognize paths, so an injected
        # UNAVAILABLE surfaces exactly where the real backend's fast-fail
        # outage does — inside the serving loop's dispatch try-block, after
        # batching and before any readback. None (production) costs one
        # attribute test per batch. RecognizerService installs/uninstalls
        # it around its start/stop so a shared pipeline never leaks faults
        # into the next service built on it.
        self.fault_injector = None
        # keyed by _step_key: (batch, h, w, dtype_str, capacity, pallas)
        self._step_cache: Dict[Tuple, Any] = {}
        self._packed_cache: Dict[Tuple, Any] = {}
        # Stage-1 cascade executables, keyed (batch, h, w, dtype_str):
        # gallery capacity never enters the stage-1 graph, so grows and
        # quantizer churn leave these warm.
        self._cascade_cache: Dict[Tuple, Any] = {}
        # Register with the gallery's async-grow machinery: when a grow is
        # imminent/in flight, the worker thread compiles THIS pipeline's
        # step for the target capacity before the swap is published, so
        # the serving thread's first call at the new tier finds a warm
        # cache instead of paying the XLA recompile (SURVEY.md §5.3) —
        # and after a later grow publishes, stale tiers' executables are
        # dropped (evict_hooks) instead of accumulating forever.
        gallery.prewarm_hooks.append(self.prewarm_capacity)
        gallery.evict_hooks.append(self.evict_below)

    def _build_step(self, batch: int, height: int, width: int,
                    capacity: Optional[int] = None, use_ivf: bool = False):
        mesh = self.gallery.mesh
        det = self.detector
        k = self.top_k
        face_size = self.face_size
        embed_net = self.embed_net
        max_faces = det.max_faces
        if self.fused_embedder:
            interpret = mesh.devices.flat[0].platform != "tpu"
            embed_apply = functools.partial(
                embedder_mod.fused_forward, embed_net, interpret=interpret)
        else:
            embed_apply = lambda p, x: embed_net.apply({"params": p}, x)  # noqa: E731
        # The gallery owns matcher selection (two-stage ivf vs pallas
        # streaming vs GSPMD global view) — the fused step inherits
        # whichever fits the mesh and capacity; _step_key re-selects if
        # the gallery grows or the quantizer (in)validates, and prewarm
        # passes the FUTURE capacity explicitly. ``use_ivf`` is pinned by
        # the caller's snapshot so a concurrent quantizer flip can't
        # change the match arity mid-build.
        match = self.gallery.match_fn(k, capacity, use_ivf=use_ivf)

        def step(det_params, emb_params, gallery_emb, gallery_valid,
                 gallery_labels, frames, ivf=()):
            # Camera frames ride host->device as uint8 when the caller has
            # them that way (4x less PCIe/tunnel traffic than f32 — H2D,
            # not compute, dominates the serving e2e estimate); the cast
            # to f32 happens here, on device.
            frames = frames.astype(jnp.float32)
            # 1) detect (dense convs; dp-sharded batch)
            outputs = det.net.apply({"params": det_params}, frames)
            boxes, det_scores, valid = detector_mod.decode_detections(
                outputs, max_faces, det.score_threshold, det.iou_threshold
            )
            # 2) align: dynamic crop+resize, all slots (invalid ones too)
            crops = image_ops.batched_crop_resize(frames, boxes, face_size)
            flat = crops.reshape((batch * max_faces, *face_size))
            # 3) embed (flax graph, or the fused pallas schedule when
            # self.fused_embedder — same params either way)
            emb = embed_apply(
                emb_params, embedder_mod.normalize_faces(flat, face_size)
            )  # [B*K, E] unit-norm
            # 4) match against the gallery (selection in gallery.match_fn:
            # two-stage ivf for a ready quantizer above its threshold,
            # GSPMD global view when sharded, pallas streaming single-chip)
            if use_ivf:
                labels, sims, _ = match(
                    emb, gallery_emb, gallery_valid, gallery_labels, ivf
                )
            else:
                labels, sims, _ = match(
                    emb, gallery_emb, gallery_valid, gallery_labels
                )
            return RecognitionResult(
                boxes=boxes,
                det_scores=det_scores,
                valid=valid,
                labels=labels.reshape((batch, max_faces, k)),
                similarities=sims.reshape((batch, max_faces, k)),
            )

        frames_sharding = NamedSharding(mesh, P(DP_AXIS, None, None))
        # ocvf-lint: boundary=jit-recompile-hazard -- THE cache-keyed builder: every serving call reaches this jit only through _step_cache misses, and warmup/prewarm compile every ladder bucket + future tier up front
        return jax.jit(step, in_shardings=(None, None, None, None, None,
                                           frames_sharding, None))

    def _step_key(self, frames: jnp.ndarray, data, ivf=None) -> Tuple:
        # Gallery capacity (and with it the pallas/GSPMD/ivf selection)
        # can change at runtime via auto-grow — bake both into the cache
        # key so a grown gallery re-selects its matcher instead of
        # re-tracing the old closure at the new shapes. All derive from
        # the SAME GalleryData/IVFDeviceData snapshots the call will
        # feed: reading ``gallery.capacity`` separately could pair a
        # stale key with new-tier arrays across a concurrent grow
        # install, forcing the retrace (and, with GSPMD at 1M rows, the
        # [Q, capacity] HBM materialization) that prewarm exists to
        # avoid. Input dtype is a trace shape too (uint8 fast transfer
        # vs f32). The ivf signature is the quantizer's static shapes —
        # a same-shape retrain republish reuses the compiled step.
        capacity = data.capacity
        return (*frames.shape, str(frames.dtype), capacity,
                self.gallery._pallas_enabled(capacity),
                None if ivf is None else ivf.shape_signature())

    @staticmethod
    def _as_device_frames(frames) -> jnp.ndarray:
        """uint8 stays uint8 (fast H2D path — cast happens in-graph);
        everything else normalizes to f32."""
        frames = jnp.asarray(frames)
        if frames.dtype != jnp.uint8:
            frames = frames.astype(jnp.float32)
        return frames

    def recognize_batch(self, frames: jnp.ndarray) -> RecognitionResult:
        """[B, H, W] frames (f32 or uint8) -> RecognitionResult; B must
        divide by dp size, and B * max_faces must too (it does when B
        does)."""
        if self.fault_injector is not None:
            self.fault_injector.on_dispatch()
        frames = self._as_device_frames(frames)
        data = self.gallery.data  # one atomic snapshot (see GalleryData)
        ivf = self.gallery._ivf_data(data)  # one epoch-checked quantizer read
        key = self._step_key(frames, data, ivf)
        # Fetch ONCE and hold the reference: a concurrent double-grow can
        # evict this tier's entry between a membership check and a second
        # subscript (evict_below runs on the grow worker).
        step = self._step_cache.get(key)
        if step is None:
            self._evict_stale_ivf(key)
            step = self._step_cache[key] = self._build_step(
                *frames.shape, capacity=data.capacity,
                use_ivf=ivf is not None)
        return step(
            self.detector.params,
            self.embed_params,
            data.embeddings,
            data.valid,
            data.labels,
            frames,
            ivf if ivf is not None else (),
        )

    def recognize_batch_packed(self, frames: jnp.ndarray) -> jnp.ndarray:
        """Same fused step, but the outputs leave the device as ONE packed
        [B, K, 6 + 2k] f32 array (see ``pack_result``) — the serving loop's
        single-readback path. Decode host-side with ``unpack_result``."""
        if self.fault_injector is not None:
            self.fault_injector.on_dispatch()
        frames = self._as_device_frames(frames)
        data = self.gallery.data  # one atomic snapshot (see GalleryData)
        ivf = self.gallery._ivf_data(data)  # one epoch-checked quantizer read
        key = self._step_key(frames, data, ivf)
        packed = self._packed_cache.get(key)  # fetch once (evict race)
        # Host-side dispatch provenance for the frame-lifecycle tracer's
        # batch spans (runtime.recognizer reads it right after the call):
        # plain attr store, best-effort — informational, never synchronized.
        self.last_dispatch_info = {"cache_hit": packed is not None,
                                   "mode": "ivf" if ivf is not None else "exact"}
        if packed is None:
            self._evict_stale_ivf(key)
            step = self._step_cache.get(key)
            if step is None:
                step = self._step_cache[key] = self._build_step(
                    *frames.shape, capacity=data.capacity,
                    use_ivf=ivf is not None)

            def packed_step(det_p, emb_p, g_emb, g_valid, g_lab, fr, iv):
                return pack_result(step(det_p, emb_p, g_emb, g_valid,
                                        g_lab, fr, iv))

            packed = self._packed_cache[key] = jax.jit(  # ocvf-lint: boundary=jit-recompile-hazard -- packed-cache fill: warmup compiles every dispatch bucket, so serving only lands here on a genuinely new (shape, capacity, matcher) key
                packed_step,
                donate_argnums=(5,) if self.donate_frames else ())
        return packed(
            self.detector.params,
            self.embed_params,
            data.embeddings,
            data.valid,
            data.labels,
            frames,
            ivf if ivf is not None else (),
        )

    def cascade_scores(self, frames) -> jnp.ndarray:
        """Compiled stage-1 pass: [B, H, W] frames (f32 or uint8) -> [B]
        face-possible probabilities on device. Cache-keyed per
        (shape, dtype) exactly like the serving steps, so every dispatch
        rung the warmup prewarmed is a jit-cache hit — the recompile
        watchdog reads ``last_cascade_info`` the way it reads
        ``last_dispatch_info`` for stage 2. The caller (the serving
        loop's cascade gate) materializes the tiny [B] result; that one
        readback IS the early-exit decision point."""
        from opencv_facerecognizer_tpu.models import cascade as cascade_mod

        gate = self.cascade
        if gate is None:
            raise RuntimeError("cascade_scores called with no cascade gate")
        frames = self._as_device_frames(frames)
        key = (*frames.shape, str(frames.dtype))
        fn = self._cascade_cache.get(key)
        # Host-side provenance for the recompile watchdog (mirrors
        # last_dispatch_info: plain attr store, informational only).
        self.last_cascade_info = {"cache_hit": fn is not None}
        if fn is None:
            net = gate.net

            def stage1(params, fr):
                # uint8 ingest frames cast on device, like the fused step.
                return cascade_mod.frame_scores(net, params,
                                                fr.astype(jnp.float32))

            fn = self._cascade_cache[key] = jax.jit(stage1)  # ocvf-lint: boundary=jit-recompile-hazard -- cache-keyed stage-1 builder: warmup compiles every (rung, ingest dtype) signature up front; serving lands here only on a genuinely new shape
        return fn(gate.params, frames)

    # ---- model-registry installs (runtime.registry swaps) ----

    def install_detector_params(self, params) -> None:
        """Publish new detector params in place (a registry detector
        swap's ``install_fn``). Detector params are jit ARGUMENTS of
        every compiled step — ``step(self.detector.params, ...)`` — so a
        same-architecture swap is one attribute store: every cached
        executable in ``_step_cache``/``_packed_cache`` stays warm and
        the very next dispatch runs the new model. Architecture changes
        do NOT go through here (they would need a new ``DetectorNet``
        and a ladder re-prewarm); the registry coordinator stages those
        as a new detector object + explicit prewarm instead."""
        self.detector.load_params(params)

    def install_cascade(self, gate) -> None:
        """Swap the stage-1 cascade gate (a registry cascade swap's
        ``install_fn``). ``cascade_scores`` reads ``self.cascade`` fresh
        per call and passes ``gate.params`` as a jit argument, so a
        same-architecture swap keeps every cached stage-1 executable
        warm. The cached closures DO hold the net object from fill time,
        so when the new gate's architecture differs (features /
        downsample) the stale executables are dropped — the next call
        per rung recompiles, which is exactly why same-config swaps are
        the supported zero-recompile path."""
        old = self.cascade
        self.cascade = gate
        if (old is None or gate is None
                or tuple(old.net.features) != tuple(gate.net.features)
                or int(old.net.downsample) != int(gate.net.downsample)):
            self._cascade_cache.clear()

    def prewarm_batch_shapes(self, batch_sizes, frame_shape,
                             dtype=np.float32) -> int:
        """Compile the packed serving step for every dispatch-bucket size
        up front (RecognizerService.warmup calls this with its bucket
        ladder): the whole point of the fixed ladder is that a partial
        batch sliced to ANY bucket finds a warm executable in
        ``_packed_cache`` instead of paying a mid-serving XLA compile.
        Each size is executed once on zero frames and blocked on, exactly
        like ``prewarm_capacity`` does for future gallery tiers. Returns
        the number of sizes compiled."""
        built = 0
        for b in sorted({int(x) for x in batch_sizes}):
            zeros = np.zeros((b, *tuple(frame_shape)), dtype)
            out = self.recognize_batch_packed(zeros)
            if hasattr(out, "block_until_ready"):
                out.block_until_ready()  # ocvf-lint: boundary=host-sync -- warmup runs BEFORE serving starts; blocking here is the point (compiles must land before the first real frame)
            if self.cascade is not None:
                # BOTH cascade stages warm per rung (and per ingest
                # dtype — the caller passes the batcher's staging dtype):
                # a mid-serving stage-1 compile would trip the same
                # recompile watchdog the ladder prewarm exists to keep
                # green.
                scores = self.cascade_scores(zeros)
                if hasattr(scores, "block_until_ready"):
                    scores.block_until_ready()  # ocvf-lint: boundary=host-sync -- warmup precedes serving; the stage-1 compile must land with the ladder's
            built += 1
        return built

    def prewarm_capacity(self, capacity: int) -> None:
        """Compile this pipeline's step(s) for a FUTURE gallery capacity.

        Called on the gallery's grow-worker thread (never the serving
        thread) for every frame-shape/dtype the pipeline has already
        served. Compilation is forced by executing each newly built step
        once against zero-filled scratch gallery arrays of the target
        tier; the jit executables land in the same function caches the
        serving thread will hit after the swap (``_step_key`` includes
        capacity + matcher selection, so the entries are keyed exactly as
        the post-grow lookups). BOTH paths are executed — the packed
        single-readback step and the unpacked ``recognize_batch`` step are
        separate XLA executables, so warming only one would leave the
        other's first post-grow call paying the full compile. Scratch
        arrays are dropped afterwards — only the executables persist.
        """
        g = self.gallery
        pallas = g._pallas_enabled(capacity)
        # Warm the EXACT-arity step for the future tier, never the ivf
        # one: prewarm's only consumers are the grow worker and the
        # early-warm thread, and the grow SPLICE invalidates the
        # quantizer (gallery._grow_worker) — so the first post-swap
        # lookup is always (ivf_sig=None, exact). Warming at the current
        # ivf signature would compile a step the swap can never hit
        # while the real post-swap key misses cold on the serving
        # thread. (The retrain that later re-enables ivf republishes
        # with fresh list shapes; its first serving call does pay a
        # compile — a known, bounded cost every first ivf enablement
        # shares, separate from the grow path this warms.)
        ivf = None
        ivf_sig = None
        served = {
            (key[0], key[1], key[2], key[3])
            for key in list(self._packed_cache) + list(self._step_cache)
        }
        if not served:
            return
        # Scratch MUST match the gallery's store_dtype: an f32 scratch on a
        # bf16 gallery warms an executable serving never hits (aval
        # mismatch -> full retrace on the serving thread post-grow).
        scratch_emb = jax.device_put(
            jnp.zeros((capacity, g.dim), g.store_dtype), g._emb_sharding
        )
        scratch_lab = jax.device_put(
            jnp.full((capacity,), g.labels_pad, jnp.int32), g._lab_sharding
        )
        scratch_val = jax.device_put(
            jnp.zeros((capacity,), bool), g._valid_sharding
        )
        for batch, height, width, dtype in served:
            new_key = (batch, height, width, dtype, capacity, pallas, ivf_sig)
            if new_key in self._packed_cache:
                continue
            step = self._step_cache.get(new_key)
            if step is None:
                step = self._build_step(batch, height, width, capacity,
                                        use_ivf=ivf is not None)
                self._step_cache[new_key] = step
            frames = jnp.zeros((batch, height, width), dtype=dtype)
            ivf_arg = ivf if ivf is not None else ()
            # Execute each once: jit compiles per concrete shape; block so
            # the caller (grow worker) only installs AFTER compiles landed.
            # ocvf-lint: boundary=host-sync -- prewarm runs on the gallery's grow-worker thread, never the serving loop; the block IS the contract (install only after compiles landed)
            jax.block_until_ready(step(
                self.detector.params, self.embed_params,
                scratch_emb, scratch_val, scratch_lab, frames, ivf_arg,
            ))

            def packed_step(det_p, emb_p, g_emb, g_valid, g_lab, fr, iv,
                            _step=step):
                return pack_result(_step(det_p, emb_p, g_emb, g_valid,
                                         g_lab, fr, iv))

            packed = jax.jit(  # ocvf-lint: boundary=jit-recompile-hazard -- prewarm builder on the grow-worker thread: compiles the future tier so the serving thread never does
                packed_step,
                donate_argnums=(5,) if self.donate_frames else ())
            packed(  # ocvf-lint: boundary=host-sync -- prewarm executes+blocks off the serving loop; install happens only after the compile landed
                self.detector.params, self.embed_params,
                scratch_emb, scratch_val, scratch_lab, frames, ivf_arg,
            ).block_until_ready()
            self._packed_cache[new_key] = packed

    def _evict_stale_ivf(self, key: Tuple) -> None:
        """Purge cached steps whose ivf shape signature was superseded by
        a retrain at the same (batch, frame, capacity, pallas) — the
        capacity-threshold eviction (``evict_below``) never sees
        same-capacity signature churn, so without this every staleness
        retrain would leak compiled executables for the process lifetime.
        In-flight calls already hold their function references."""
        sig = key[6]
        if sig is None:
            return
        for cache in (self._step_cache, self._packed_cache):
            for stale in [k2 for k2 in list(cache)
                          if k2[:6] == key[:6] and k2[6] not in (None, sig)]:
                cache.pop(stale, None)

    def evict_below(self, min_capacity: int) -> None:
        """Drop compiled steps for gallery tiers strictly below
        ``min_capacity`` (called from the gallery after a later grow
        publishes — see ``ShardedGallery.evict_hooks``). In-flight calls
        already hold their function references; only the cache forgets."""
        for cache in (self._step_cache, self._packed_cache):
            for key in [k for k in list(cache) if k[4] < min_capacity]:
                cache.pop(key, None)
