"""Mesh construction helpers.

One place decides how the available chips are split between the data-parallel
(``dp``) and gallery-tensor-parallel (``tp``) axes, so every jitted graph in
the framework agrees on axis names.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"
TP_AXIS = "tp"


def make_mesh(
    dp: Optional[int] = None,
    tp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, tp) mesh over ``devices`` (default: all local devices).

    With neither axis given, everything goes to ``tp`` — gallery sharding is
    the axis that changes peak capacity, while dp can also be served by
    larger per-chip batches. Given one axis, the other takes the remainder;
    given both, they must factor the device count exactly.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and tp is None:
        dp, tp = 1, n
    elif dp is None:
        if n % tp:
            raise ValueError(f"tp={tp} does not divide device count {n}")
        dp = n // tp
    elif tp is None:
        if n % dp:
            raise ValueError(f"dp={dp} does not divide device count {n}")
        tp = n // dp
    if dp * tp != n:
        raise ValueError(f"dp*tp = {dp}*{tp} != device count {n}")
    arr = np.asarray(devices).reshape(dp, tp)
    return Mesh(arr, (DP_AXIS, TP_AXIS))
