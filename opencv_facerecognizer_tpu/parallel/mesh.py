"""Mesh construction helpers.

One place decides how the available chips are split between the data-parallel
(``dp``) and gallery-tensor-parallel (``tp``) axes, so every jitted graph in
the framework agrees on axis names.

Multi-host: ``initialize_multihost()`` below brings up the jax distributed
runtime so ``jax.devices()`` spans every host's chips; ``make_mesh`` then
builds the global mesh unchanged (GSPMD inserts ICI collectives within a
slice and DCN collectives across slices — the comm-backend split the
reference delegated to ROS/NCCL-era transports is entirely XLA's job here,
SURVEY.md §5.8). Lay dp across hosts and tp within a slice so the gallery's
all-gather rides ICI.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"
TP_AXIS = "tp"


def _distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists (jax >= 0.5);
    on older jax fall back to probing the module-level client state — the
    call must degrade to "not initialized", never AttributeError, on any
    jax this repo's env gates allow."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    state = getattr(jax.distributed, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the jax distributed runtime when running multi-host.

    The TPU-native analog of the reference's process-level transport
    bootstrap: after this, ``jax.devices()`` lists every host's chips and
    the same ``make_mesh``/GSPMD graphs are *intended* to scale across DCN
    with no further code changes. Honesty note: this machine has one host,
    so the multi-host path is exercised only with a mocked
    ``jax.distributed`` (tests/test_parallel.py) — the DCN-scaling claim is
    the documented design, not a measured result here.
    Arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``);
    passing any argument explicitly also triggers initialization (jax then
    autodetects whatever was left out, e.g. the coordinator on a TPU pod).

    Returns True when the distributed runtime was (already) initialized,
    False when neither arguments nor env vars ask for multi-host — callers
    never need to branch.
    """
    if _distributed_is_initialized():
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if (coordinator_address is None and env_np is None
            and num_processes is None and process_id is None):
        return False  # nothing asked for multi-host; stay single-process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=(
            num_processes if num_processes is not None
            else int(env_np) if env_np else None
        ),
        process_id=(
            process_id if process_id is not None
            else int(env_pid) if env_pid else None
        ),
    )
    return True


def make_mesh(
    dp: Optional[int] = None,
    tp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, tp) mesh over ``devices`` (default: all local devices).

    With neither axis given, everything goes to ``tp`` — gallery sharding is
    the axis that changes peak capacity, while dp can also be served by
    larger per-chip batches. Given one axis, the other takes the remainder;
    given both, they must factor the device count exactly.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None and tp is None:
        dp, tp = 1, n
    elif dp is None:
        if n % tp:
            raise ValueError(f"tp={tp} does not divide device count {n}")
        dp = n // tp
    elif tp is None:
        if n % dp:
            raise ValueError(f"dp={dp} does not divide device count {n}")
        tp = n // dp
    if dp * tp != n:
        raise ValueError(f"dp*tp = {dp}*{tp} != device count {n}")
    arr = np.asarray(devices).reshape(dp, tp)
    return Mesh(arr, (DP_AXIS, TP_AXIS))
