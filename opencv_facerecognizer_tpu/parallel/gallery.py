"""Sharded enrolled gallery: the TP axis (BASELINE.json:5: "NearestNeighbor
.predict becomes a sharded cosine-similarity matmul against the enrolled
gallery held in TPU HBM").

Design:
- Fixed ``capacity`` (static shapes; XLA recompiles nothing as people
  enroll). Rows beyond ``size`` are invalid and masked to -inf similarity.
- Embeddings live sharded row-wise over the ``tp`` mesh axis; each chip
  computes a [Q, C/tp] bf16 similarity block on its MXU against its HBM
  shard, takes a local top-k, then one small ``all_gather`` of [Q, k]
  candidates per chip merges to the global top-k — the classic
  sharded-matmul + argmax-reduction pattern (SURVEY.md §2.3 TP row).
  Collective traffic is O(Q * k * tp), never O(Q * capacity).
- Labels are tiny ([capacity] int32), so they stay replicated.
- Queries are sharded over ``dp`` and replicated over ``tp``; outputs come
  back sharded over ``dp``.
- Enrolment writes and the double-buffered atomic swap (``runtime``'s
  model-reload-without-drop, SURVEY.md §5.3) happen host-side via
  ``jax.device_put`` with the same shardings.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS

# numpy, not jnp: a module-level jnp scalar initializes the JAX backend at
# IMPORT time, which blocks every importer (even transport-only child
# processes) whenever the accelerator is unreachable. jnp ops accept the
# numpy scalar identically.
NEG_INF = np.float32(-1e30)


def take_labels_with_sentinel(labels, idx, labels_pad: int):
    """Gather labels for top-k indices, mapping sentinel ``idx == -1`` slots
    (a shard/gallery with fewer than k valid rows) to the pad label — a
    clamped/wrapped gather would pair a real subject's label with the
    -1e30 sentinel sim."""
    return jnp.where(
        idx < 0,
        jnp.int32(labels_pad),
        jnp.take(labels, jnp.maximum(idx, 0)),
    )


def match_global(q, g, valid, labels, *, k: int, mesh: Mesh):
    """Global-view sharded match: the GSPMD formulation.

    Written on full arrays with sharding *annotations* instead of shard_map
    (pick a mesh, annotate, let XLA insert the collectives): the similarity
    matmul is computed shard-local (g row-sharded over tp -> sims
    column-sharded), then a two-phase top-k — phase 1 per tp chunk (local,
    no comms), phase 2 over the tp*k gathered candidates — keeps collective
    traffic O(Q * k * tp) instead of all-gathering [Q, capacity].

    Chosen over shard_map for a concrete reason: on the axon PJRT backend a
    shard_map dispatch costs ~125 ms even on a 1x1 mesh (measured), while
    jit-with-shardings compiles to the exact same local compute and runs in
    ~0.06 ms single-chip.

    q [Q, D]; g [C, D] sharded P(tp, None); valid [C]; labels [C].
    Returns (labels [Q, k], sims [Q, k], gallery indices [Q, k]).
    """
    tp = mesh.shape[TP_AXIS]
    cap = g.shape[0]
    chunk = cap // tp
    # MXU block: bf16 operands, f32 accumulation.
    sims = jax.lax.dot_general(
        q.astype(jnp.bfloat16),
        g.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Q, C]
    sims = jnp.where(valid[None, :], sims, NEG_INF)
    qn = sims.shape[0]
    if tp == 1:
        # Singleton tp: the two-phase split is identical math but the
        # reshape + sharding constraint break XLA's matmul->top_k fusion
        # (measured on v5e: 2.40 vs 1.00 ms/batch for the whole fused
        # serving step at 16k rows) — take the direct top_k.
        top_vals, top_gidx = jax.lax.top_k(sims, min(k, cap))
        return jnp.take(labels, top_gidx), top_vals, top_gidx
    # Phase 1: per-chunk top-k, chunk == tp shard (the constraint pins the
    # reshape to be shard-local).
    s3 = sims.reshape(qn, tp, chunk)
    s3 = jax.lax.with_sharding_constraint(
        s3, NamedSharding(mesh, P(DP_AXIS, TP_AXIS, None))
    )
    local_k = min(k, chunk)
    vals, idx = jax.lax.top_k(s3, local_k)  # [Q, tp, local_k]
    gidx = idx + (jnp.arange(tp, dtype=jnp.int32) * chunk)[None, :, None]
    # Phase 2: merge the tp*local_k candidates (tiny; XLA gathers these).
    vals2 = vals.reshape(qn, tp * local_k)
    gidx2 = gidx.reshape(qn, tp * local_k)
    out_k = min(k, tp * local_k)
    top_vals, pos = jax.lax.top_k(vals2, out_k)
    top_gidx = jnp.take_along_axis(gidx2, pos, axis=1)
    top_labels = jnp.take(labels, top_gidx)
    return top_labels, top_vals, top_gidx


def match_pod_pallas(q, g, valid, labels, *, k: int, mesh: Mesh,
                     interpret: bool = False, labels_pad: int = -1):
    """Pod-scale matcher: ``shard_map`` over tp, pallas streaming kernel
    per shard, collective merge of the tiny candidate sets.

    Each chip streams its [capacity/tp, D] gallery shard through
    ``ops.pallas_match.streaming_match_topk`` (local [Q, k] top-k, no
    [Q, capacity/tp] materialization), then one ``all_gather`` over tp of
    [Q, k] values+indices — O(Q * k * tp) ICI traffic — and a final
    ``lax.top_k`` merge on every chip. This is the multi-chip form of the
    pallas fast path: GSPMD cannot partition a custom call, so the shard
    decomposition is written explicitly here.

    Not the serving default on this machine: the axon tunnel charges
    ~125 ms per shard_map dispatch (measured — see ``match_global``),
    which buries the kernel win. On a real pod slice, dispatch is normal
    and this path pairs the kernel's HBM savings with tp scaling; it is
    CPU-mesh tested in interpret mode either way.

    Shapes/shardings: q [Q, D] dp-sharded; g [C, D] tp row-sharded;
    valid [C] tp-sharded; labels [C] replicated. Returns the same
    (labels [Q, k], sims [Q, k], gallery indices [Q, k]) as match_global.
    """
    from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk

    tp = mesh.shape[TP_AXIS]
    chunk = g.shape[0] // tp

    def shard_body(q_l, g_l, valid_l, labels_l):
        vals, idx = streaming_match_topk(
            q_l, g_l, valid_l, k=min(k, chunk), interpret=interpret
        )
        offset = jax.lax.axis_index(TP_AXIS).astype(jnp.int32) * chunk
        # A shard with fewer valid rows than k emits sentinel -1 indices;
        # keep them -1 instead of offsetting into a neighbor shard's rows.
        idx = jnp.where(idx < 0, -1, idx + offset)
        # One tiled gather each -> [Q, tp*local_k] candidates on every chip.
        cand_v = jax.lax.all_gather(vals, TP_AXIS, axis=1, tiled=True)
        cand_i = jax.lax.all_gather(idx, TP_AXIS, axis=1, tiled=True)
        out_k = min(k, cand_v.shape[1])
        top_v, pos = jax.lax.top_k(cand_v, out_k)
        top_i = jnp.take_along_axis(cand_i, pos, axis=1)
        return take_labels_with_sentinel(labels_l, top_i, labels_pad), top_v, top_i

    specs = dict(
        mesh=mesh,
        in_specs=(P(DP_AXIS, None), P(TP_AXIS, None), P(TP_AXIS), P()),
        out_specs=(P(DP_AXIS, None), P(DP_AXIS, None), P(DP_AXIS, None)),
    )
    if hasattr(jax, "shard_map"):
        mapped = jax.shard_map(shard_body, check_vma=False, **specs)
    else:
        # jax < 0.6: shard_map lives in jax.experimental and the
        # replication check is spelled check_rep.
        from jax.experimental.shard_map import shard_map as _shard_map

        mapped = _shard_map(shard_body, check_rep=False, **specs)
    return mapped(q, g, valid, labels)


class EmbeddingDimMismatchError(ValueError):
    """A gallery swap was attempted across embedding dimensions. A new
    embedder with a different D produces vectors in a DIFFERENT space —
    installing them over rows scored in the old space would silently mix
    embedder versions in one served shard set. The only sanctioned route
    is the staged re-embed rollout (``runtime.rollout``): re-embed every
    row into the new space, fence the WAL with a cutover record, then
    install the staged set whole. Subclasses ``ValueError`` so pre-rollout
    callers that caught the old dim-mismatch error keep working."""


class GalleryData(NamedTuple):
    """One immutable snapshot of the device-visible gallery state.

    Reader side of the concurrency story: all reads go through a single
    ``self._data`` attribute load (atomic at Python level), so a reader can
    never observe a mixed snapshot (e.g. new valid mask against old
    embeddings). Writer side: ``add``/``reset``/``swap_from`` serialize on
    an internal lock, so concurrent enrolments can't both claim the same
    rows."""

    embeddings: jnp.ndarray  # [capacity, dim], P(tp, None)
    labels: jnp.ndarray  # [capacity], replicated
    valid: jnp.ndarray  # [capacity], P(tp)
    size: int
    #: gallery ``_epoch`` at snapshot build — reset/swap_from/load_snapshot
    #: bump it. Pairs this snapshot with derived state (the IVF quantizer
    #: stamps its publishes with the same counter): a reader that took the
    #: two snapshots non-atomically rejects a cross-epoch pair instead of
    #: matching one row set against another's inverted lists.
    epoch: int = 0

    @property
    def capacity(self) -> int:
        """Tier of THIS snapshot. Cache keys must derive from the snapshot
        (not ``gallery.capacity``) so a concurrent grow can never pair one
        tier's compiled step with another tier's arrays — the mixed pairing
        forces an XLA retrace on the serving thread, the exact stall
        async-grow prewarm exists to avoid."""
        return int(self.embeddings.shape[0])


class ShardedGallery:
    """Enrolled gallery of L2-normalized embeddings, row-sharded over tp."""

    #: capacity above which the pallas streaming kernel beats the XLA
    #: materialize+top_k path on real hardware (measured on v5e: 1.08x at
    #: 131k rows, 1.73x at 1M; parity/noise at 16k).
    PALLAS_MIN_CAPACITY = 65536

    #: capacity above which ``match_mode="auto"`` switches to the
    #: two-stage IVF path (when a ready quantizer is attached): the exact
    #: scan is linear in capacity (BENCH_r05: 1.356 ms/batch at 262k,
    #: 3.607 at 1M) while the shortlist+rerank cost scales with the
    #: probed cells — below this tier the exact scan is already cheap
    #: and the IVF recall trade buys nothing.
    IVF_MIN_CAPACITY = 262144

    #: start background-compiling the next tier once fill crosses this
    #: fraction (async_grow mode), so the eventual grow swaps to an
    #: already-compiled graph (SURVEY.md §5.3 elastic recovery).
    PREWARM_FILL_FRACTION = 0.75

    def __init__(
        self,
        capacity: int,
        dim: int,
        mesh: Mesh,
        labels_pad: int = -1,
        use_pallas: Optional[bool] = None,
        async_grow: bool = False,
        store_dtype: Any = jnp.float32,
        embedder_version: int = 1,
    ):
        self.mesh = mesh
        #: version of the embedder whose space EVERY row in this gallery
        #: lives in — one gallery never mixes versions (the rollout
        #: subsystem's fencing invariant, ``runtime.rollout``). Stamped
        #: into checkpoint headers and WAL rows by ``StateLifecycle``;
        #: changed only by a whole-set install (``load_snapshot`` /
        #: ``swap_from`` adopting the donor's version) — never row-wise.
        self.embedder_version = int(embedder_version)
        self._use_pallas_cfg = use_pallas
        tp = mesh.shape[TP_AXIS]
        # Round capacity up so every tp shard is equal (static shapes).
        self.capacity = int(np.ceil(capacity / tp) * tp)
        self.dim = int(dim)
        self.labels_pad = labels_pad
        #: device dtype of the gallery rows. Both matchers already compute
        #: the similarity matmul in bf16 operands / f32 accumulation
        #: (match_global:76, pallas_match kernel), so ``store_dtype=
        #: jnp.bfloat16`` is NUMERICALLY IDENTICAL on the match path while
        #: halving gallery HBM and H2D bytes (1 GB -> 0.5 GB at 1M rows on
        #: the measured tunnel). Host mirrors stay f32 (enrolment truth,
        #: snapshot/serialization unchanged); the cast happens host-side at
        #: install so the transfer itself is half-width. Default stays f32
        #: for drop-in familiarity.
        self.store_dtype = jnp.dtype(store_dtype)
        self._emb_sharding = NamedSharding(mesh, P(TP_AXIS, None))
        self._lab_sharding = NamedSharding(mesh, P())
        self._valid_sharding = NamedSharding(mesh, P(TP_AXIS))
        self._host_emb = np.zeros((self.capacity, dim), np.float32)
        self._host_lab = np.full((self.capacity,), labels_pad, np.int32)
        self._host_val = np.zeros((self.capacity,), bool)
        self._write_lock = threading.Lock()
        self.grow_count = 0
        # ---- async (off-the-serving-path) growth state ----
        # ``async_grow=True`` turns an overflowing add() into: stage the
        # rows host-side, compile the next tier's graphs on a background
        # thread (prewarm_hooks), build + install the grown snapshot there,
        # publish atomically. Serving threads NEVER pay the XLA recompile;
        # the cost moves to enrolment-to-matchable latency (observable via
        # ``pending_rows`` / ``wait_ready``). Default stays synchronous:
        # enrolment tools that want rows matchable on return keep that
        # contract.
        self.async_grow = bool(async_grow)
        #: callables invoked with the TARGET capacity on the grow worker
        #: thread BEFORE the grown snapshot is installed — the fused
        #: pipeline registers its step-compile here (parallel.pipeline).
        self.prewarm_hooks = []
        #: callables invoked with a capacity THRESHOLD after a grow
        #: publishes: pipelines drop compiled entries for tiers strictly
        #: below it. Growing A->B->C evicts A's executables when C installs
        #: (B survives for readers that took their snapshot before C) —
        #: without this, crossing 16k->1M (7 tiers x shapes x dtypes)
        #: permanently retains every stale tier's executables.
        self.evict_hooks = []
        self._pending: list = []  # [[emb_rows, lab_rows, normalized?]] staged
        self._pending_count = 0
        self._growing = False
        self._grow_thread: Optional[threading.Thread] = None
        self._grow_done = threading.Event()
        self._grow_done.set()
        self._epoch = 0  # bumped by reset/swap_from to invalidate a grow
        self._warmed_capacities = set()
        self._warm_events = {}  # capacity -> Event, set when its warm ends
        self._chunk_jit = None  # (key, zeros, update) for _chunked_emb_put
        self._bitcast_jit = None  # u16 -> bf16 device bitcast (_put_emb)
        self.last_grow_info: dict = {}
        # ---- optional IVF coarse quantizer (parallel.quantizer) ----
        # Derived state: the gallery drives every lifecycle edge —
        # incremental assignment on add, invalidation on reset/
        # load_snapshot/swap_from/async-grow splice, staleness pokes.
        # ``match_mode``: "exact" never uses it, "ivf" always (when
        # ready), "auto" switches at IVF_MIN_CAPACITY.
        self.quantizer = None
        self.match_mode = "exact"
        self._data = GalleryData(
            embeddings=jax.device_put(
                jnp.zeros((self.capacity, dim), self.store_dtype),
                self._emb_sharding
            ),
            labels=jax.device_put(
                jnp.full((self.capacity,), labels_pad, jnp.int32), self._lab_sharding
            ),
            valid=jax.device_put(
                jnp.zeros((self.capacity,), bool), self._valid_sharding
            ),
            size=0,
        )
        self._match_cache = {}

    # Single-attribute snapshot: the only device-state read path.
    @property
    def data(self) -> GalleryData:
        return self._data

    @property
    def embeddings(self) -> jnp.ndarray:
        return self._data.embeddings

    @property
    def labels(self) -> jnp.ndarray:
        return self._data.labels

    @property
    def valid(self) -> jnp.ndarray:
        return self._data.valid

    @property
    def size(self) -> int:
        return self._data.size

    # ---- enrolment (host-side; serving never blocks on these) ----

    @staticmethod
    def _normalize_rows(embeddings: np.ndarray) -> np.ndarray:
        return embeddings / np.maximum(
            np.linalg.norm(embeddings, axis=-1, keepdims=True), 1e-12
        )

    def _host_cast(self, x: np.ndarray) -> np.ndarray:
        """Cast to store_dtype on the host so the H2D wire carries the
        narrow bytes (ml_dtypes' f32->bf16 astype measures ~640M el/s —
        not a bottleneck)."""
        if self.store_dtype == np.float32:
            return np.asarray(x, np.float32)
        return np.asarray(x).astype(self.store_dtype)

    def _put_emb(self, emb_np: np.ndarray) -> jnp.ndarray:
        """device_put of gallery rows (``_emb_sharding``) in store_dtype
        width. bf16 ships as uint16 + a device-side bitcast: device_put of
        an ml_dtypes numpy array misses PJRT's zero-copy path on this
        backend (measured 25x slower per byte than f32 in sync-poll mode),
        while the same bits as a standard uint16 array ride the fast path
        and the bitcast is a free layout op on device."""
        cast = self._host_cast(emb_np)
        if self.store_dtype != jnp.bfloat16:
            return jax.device_put(cast, self._emb_sharding)
        if self._bitcast_jit is None:
            self._bitcast_jit = jax.jit(
                lambda a: jax.lax.bitcast_convert_type(a, jnp.bfloat16),
                out_shardings=self._emb_sharding)
        dev_u16 = jax.device_put(cast.view(np.uint16), self._emb_sharding)
        return self._bitcast_jit(dev_u16)

    def add(self, embeddings: np.ndarray, labels: np.ndarray) -> None:
        """Append L2-normalized rows, auto-growing on overflow.

        Synchronous mode (default): growth doubles capacity (tp-aligned)
        and installs the bigger arrays before returning — rows are
        matchable on return, but the static-shape change means the matcher
        (and the fused pipeline step) recompile once on the next call,
        stalling that serving batch by seconds on real hardware.

        ``async_grow=True`` (the serving configuration): an overflowing
        add stages its rows host-side RAW and returns immediately — even
        the L2 normalization runs on the grow worker (measured 16 s for
        920k rows on a 1-core host; an enrolling connector thread must not
        pay that). The worker compiles the next tier's graphs
        (``prewarm_hooks``), normalizes + splices the staged rows, uploads
        the grown snapshot, WAITS for device residency (serving keeps
        reading the old tier — otherwise the first new-tier call absorbs
        the multi-second H2D of a large gallery; measured 36 s at 1M rows
        on the tunneled backend), then publishes atomically. Rows become
        matchable when ``wait_ready`` unblocks (``pending_rows`` exposes
        the in-flight count). Additionally, any add that fills the gallery
        past ``PREWARM_FILL_FRACTION`` kicks the next tier's compile
        early, so the eventual grow usually only pays copy + upload.
        """
        embeddings = np.asarray(embeddings, np.float32)
        labels = np.asarray(labels, np.int32)
        n = embeddings.shape[0]
        # Optimistic branch predict, OUTSIDE the lock: the sync path needs
        # normalized rows, and normalizing a large add while holding the
        # write lock would block every other enroller behind it. A raced
        # prediction is only a cost shift: predicted-sync-but-staged wastes
        # one normalization (flagged True, worker skips), predicted-staged-
        # but-sync normalizes under the lock (rare; both windows are the
        # gap between this read and the locked re-check).
        normalized = not (self.async_grow and (self._growing or self._pending
                                               or self.size + n > self.capacity))
        if normalized:
            embeddings = self._normalize_rows(embeddings)  # dividing copy
        else:
            # Private copy before staging: asarray is a no-copy view of a
            # float32 input, and a staged-by-reference buffer the caller
            # refills after add() returns would enroll garbage (the worker
            # may not splice for seconds). ~0.3 s memcpy at 920k rows vs
            # the 16 s normalization being deferred.
            embeddings = np.array(embeddings, copy=True)
        start_worker = False
        evict_below = None
        with self._write_lock:
            size = self.size
            if self.async_grow and (self._growing or self._pending
                                    or size + n > self.capacity):
                # Stage RAW; the worker owns all host-array mutation while
                # a grow is in flight (a direct write here would race the
                # worker's copy of the old arrays) and normalizes staged
                # rows off this thread. Entries are mutable lists so the
                # worker can swap in the normalized array in place:
                # [rows, labels, normalized?]. Non-empty pending with no
                # worker means a previous grow FAILED: later adds must
                # queue behind the stranded rows (enrolment order), and
                # this add restarts the worker to retry them. Labels are
                # copied HERE, at the staging site: asarray of an int32
                # input is a no-copy view, and the worker may splice
                # seconds after add() returns — a caller reusing its label
                # buffer would otherwise enroll wrong identities (the
                # embeddings already got their private copy above, or are
                # a fresh dividing copy on the lost-race path).
                self._pending.append([embeddings, np.array(labels, copy=True),
                                      normalized])
                self._pending_count += n
                if not self._growing:
                    self._growing = True
                    self._grow_done.clear()
                    start_worker = True
            else:
                if size + n > self.capacity:
                    evict_below = self.capacity  # tier being replaced
                    self._grow_locked(size + n)
                # Host mirrors are the source of truth for enrolment: a
                # device readback here would trigger the axon backend's
                # sync-poll mode (see runtime.recognizer module docstring).
                if not normalized:  # lost the branch-predict race
                    embeddings = self._normalize_rows(embeddings)
                self._host_emb[size : size + n] = embeddings
                self._host_lab[size : size + n] = labels
                self._host_val[size : size + n] = True
                if self.quantizer is not None:
                    # Incremental IVF assignment, under the same write
                    # lock as the mirror update: the rows land in their
                    # cells (or the spill) before the snapshot below
                    # publishes them as matchable, so the two-stage path
                    # never misses a row the exact path would find.
                    self.quantizer.on_rows_added(embeddings, size)
                self._install(self._host_emb, self._host_lab, self._host_val,
                              size + n)
        if evict_below is not None:
            self._evict_stale(evict_below)
        if not self._growing:
            # Staleness poke outside the lock (a retrain mid-grow would
            # only be invalidated by the splice anyway).
            self._poke_quantizer()
        if start_worker:
            self._grow_thread = threading.Thread(
                target=self._grow_worker, daemon=True, name="gallery-grow"
            )
            self._grow_thread.start()
        elif (self.async_grow and not self._growing
              and self.size >= self.PREWARM_FILL_FRACTION * self.capacity):
            # Early warm: compile the next tier while serving continues at
            # the current one, so the eventual grow swap finds warm caches.
            self._prewarm_async(self._next_capacity(self.capacity + 1))

    @property
    def pending_rows(self) -> int:
        """Rows staged by async grow, not yet matchable."""
        return self._pending_count

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the current async grow attempt finishes. On success
        ``pending_rows == 0`` and the staged rows are matchable; a failed
        attempt leaves ``pending_rows > 0`` with the exception recorded in
        ``last_grow_info["error"]`` (the next add() retries the grow)."""
        return self._grow_done.wait(timeout)

    def _next_capacity(self, needed: int) -> int:
        tp = self.mesh.shape[TP_AXIS]
        new_capacity = max(self.capacity, 1)
        while new_capacity < needed:
            new_capacity *= 2
        return int(np.ceil(new_capacity / tp) * tp)

    def _run_prewarm_hooks(self, capacity: int, info: dict) -> None:
        """Warm one tier exactly once across threads: the first caller
        (early-warm thread or grow worker) compiles; any concurrent caller
        for the same tier WAITS on its completion event instead of racing
        a duplicate compile (duplicate scratch arrays at a 1M-row tier
        are a device-memory spike, and the grow worker must not install
        before the compile has landed either way)."""
        import time as _time

        with self._write_lock:
            if capacity in self._warmed_capacities:
                info["prewarm_s"] = 0.0
                return
            ev = self._warm_events.get(capacity)
            if ev is None:
                ev = self._warm_events[capacity] = threading.Event()
                owner = True
            else:
                owner = False
        if not owner:
            ev.wait(timeout=600)
            info["prewarm_s"] = 0.0  # another thread paid for it
            return
        t0 = _time.perf_counter()
        try:
            for hook in list(self.prewarm_hooks):
                try:
                    hook(capacity)
                except Exception as e:  # serving must survive a failed
                    # warm: the fallback is the old behavior (compile on
                    # first call).
                    info.setdefault("prewarm_errors", []).append(repr(e))
        finally:
            with self._write_lock:
                self._warmed_capacities.add(capacity)
                self._warm_events.pop(capacity, None)
            ev.set()
        info["prewarm_s"] = round(_time.perf_counter() - t0, 3)

    def _prewarm_async(self, capacity: int) -> None:
        with self._write_lock:
            started = (capacity in self._warmed_capacities
                       or capacity in self._warm_events)
        if started or not self.prewarm_hooks:
            return
        threading.Thread(
            target=self._run_prewarm_hooks, args=(capacity, {}),
            daemon=True, name="gallery-prewarm",
        ).start()

    #: grow worker gives up waiting for device residency after this long
    #: and publishes anyway (availability over stall avoidance); generous
    #: because a 1M-row gallery is ~1 GB over a ~30 MB/s tunnel.
    RESIDENCY_TIMEOUT_S = 300.0

    @staticmethod
    def _await_residency(data: "GalleryData", timeout_s: float,
                         cancel=None, info=None) -> bool:
        """Poll ``jax.Array.is_ready`` (non-blocking — a synchronous
        readback would drop the process into the axon backend's ~100 ms
        poll mode) until the snapshot's H2D transfers complete. True on
        resident, False on timeout or a backend without is_ready.
        ``cancel()`` returning True aborts the wait immediately — a
        reset/swap_from that doomed this snapshot must not keep the
        worker polling for up to the full timeout."""
        import time as _time

        arrays = (data.embeddings, data.labels, data.valid)
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if cancel is not None and cancel():
                return True  # doomed snapshot; publish check discards it
            try:
                if all(a.is_ready() for a in arrays):
                    return True
            except (AttributeError, NotImplementedError):
                return True  # no is_ready on this backend: don't block
            except Exception as e:
                # A transient backend error must not silently skip the
                # wait (publishing early recreates the 36 s first-call
                # stall this path exists to prevent) — record and keep
                # polling until resident or timeout.
                if info is not None and "residency_probe_error" not in info:
                    info["residency_probe_error"] = repr(e)
            _time.sleep(0.02)
        return False

    def _grow_worker(self) -> None:
        """Off-the-serving-path growth: compile (hooks) -> copy ->
        normalize staged rows -> splice -> upload -> await residency ->
        atomic publish. Serving threads keep reading the OLD snapshot
        until the grown arrays are device-resident — publishing earlier
        makes the first new-tier call absorb the whole H2D transfer
        (measured 36 s for a 1M-row gallery on the tunneled backend).
        ``reset``/``swap_from`` bump ``_epoch`` to invalidate an in-flight
        grow; the epoch is re-checked at splice AND at publish, so a
        reset during the residency wait wins and the stale snapshot is
        dropped."""
        import time as _time

        info = {}
        spliced = None  # popped-but-unpublished entries; see except below
        epoch = None
        try:
            while True:
                spliced = None
                # Per-round flags: a round-1 timeout must not misreport a
                # round-2 publish that DID wait successfully.
                info.pop("residency_timeout", None)
                info.pop("residency_probe_error", None)
                with self._write_lock:
                    if not self._pending:
                        self._growing = False
                        self._grow_done.set()
                        self.last_grow_info = info
                        return
                    epoch = self._epoch
                    size = self.size
                    pending_n = self._pending_count
                    old_emb, old_lab, old_val = (
                        self._host_emb, self._host_lab, self._host_val,
                    )
                    old_cap = self.capacity
                target = self._next_capacity(size + pending_n)
                # Compile the new tier's graphs BEFORE taking rows live.
                self._run_prewarm_hooks(target, info)
                t0 = _time.perf_counter()
                emb = np.zeros((target, self.dim), np.float32)
                lab = np.full((target,), self.labels_pad, np.int32)
                val = np.zeros((target,), bool)
                emb[:old_cap] = old_emb
                lab[:old_cap] = old_lab
                val[:old_cap] = old_val
                info["copy_s"] = round(_time.perf_counter() - t0, 3)
                # Normalize staged rows here, not on the enrolling thread
                # (add() stages raw). In-place entry mutation is GIL-atomic
                # and safe against a concurrent reset clearing the list —
                # a cleared entry is garbage either way. Entries staged
                # after this sweep stay unnormalized and are left for the
                # next worker round (the splice below stops at the first
                # unnormalized entry, preserving enrolment order).
                t0 = _time.perf_counter()
                with self._write_lock:
                    sweep = list(self._pending)
                for entry in sweep:
                    if not entry[2]:
                        entry[0] = self._normalize_rows(entry[0])
                        entry[2] = True
                info["normalize_s"] = round(_time.perf_counter() - t0, 3)
                with self._write_lock:
                    if self._epoch != epoch:
                        # reset/swap_from superseded this grow; drop it and
                        # re-examine what (if anything) is still pending.
                        continue
                    # Splice every normalized entry that fits (adds staged
                    # after the sweep, or overflowing the target, loop for
                    # another round). Popped entries are NOT yet published:
                    # counts and host mirrors move at publish time, and an
                    # epoch bump in between discards them exactly like a
                    # reset discards pending rows.
                    fits = []
                    n_fit = 0
                    while self._pending:
                        entry = self._pending[0]
                        if not entry[2] or size + n_fit + len(entry[0]) > target:
                            break
                        fits.append(entry)
                        n_fit += len(entry[0])
                        self._pending.pop(0)
                    spliced = fits  # restored by the except path if the
                    # upload below dies before these rows publish
                    pos = size
                    for e_rows, l_rows, _ in fits:
                        emb[pos : pos + len(e_rows)] = e_rows
                        lab[pos : pos + len(e_rows)] = l_rows
                        val[pos : pos + len(e_rows)] = True
                        pos += len(e_rows)
                # Upload OUTSIDE the lock and wait for residency while
                # serving threads still read the old tier. A reset/swap
                # epoch bump cancels the wait immediately.
                t0 = _time.perf_counter()
                new_data = self._build_snapshot(
                    emb, lab, val, pos, chunked=True,
                    cancel=lambda: self._epoch != epoch, info=info,
                    epoch=epoch)
                if not self._await_residency(new_data, self.RESIDENCY_TIMEOUT_S,
                                             cancel=lambda: self._epoch != epoch,
                                             info=info):
                    info["residency_timeout"] = True
                info["upload_wait_s"] = round(_time.perf_counter() - t0, 3)
                t0 = _time.perf_counter()
                with self._write_lock:
                    if self._epoch != epoch:
                        continue  # a reset/swap during the wait wins; the
                        # spliced rows are discarded exactly as the reset
                        # discarded the rest of pending
                    self._host_emb, self._host_lab, self._host_val = emb, lab, val
                    self.capacity = target
                    self.grow_count += 1
                    self._pending_count -= n_fit
                    if self.quantizer is not None:
                        # A splice lands a large staged row set at once —
                        # invalidate instead of assigning thousands of
                        # rows under the write lock; serving falls back
                        # to the exact matcher until the background
                        # retrain (poked below) republishes.
                        self.quantizer.invalidate()
                    self._data = new_data
                    spliced = None  # published: nothing to restore
                info["install_s"] = round(_time.perf_counter() - t0, 3)
                # Outside the lock: drop compiled entries for tiers below
                # the one just replaced (see evict_hooks).
                self._evict_stale(old_cap)
                self._poke_quantizer()
        except Exception as e:  # never leave waiters hanging
            info["error"] = repr(e)
            with self._write_lock:
                if spliced and self._epoch == epoch:
                    # Popped but never published (e.g. device_put died at
                    # the new tier): put the rows back at the head so
                    # ``pending_rows`` stays truthful and the next add()
                    # retries them in enrolment order. On an epoch bump
                    # they stay dropped, like the rest of pending.
                    self._pending[:0] = spliced
                self._growing = False
                self._grow_done.set()
                self.last_grow_info = info

    def _grow_locked(self, needed: int) -> None:
        """Double capacity (tp-aligned) until ``needed`` rows fit; caller
        holds the write lock."""
        tp = self.mesh.shape[TP_AXIS]
        new_capacity = max(self.capacity, 1)
        while new_capacity < needed:
            new_capacity *= 2
        new_capacity = int(np.ceil(new_capacity / tp) * tp)
        emb = np.zeros((new_capacity, self.dim), np.float32)
        lab = np.full((new_capacity,), self.labels_pad, np.int32)
        val = np.zeros((new_capacity,), bool)
        emb[: self.capacity] = self._host_emb
        lab[: self.capacity] = self._host_lab
        val[: self.capacity] = self._host_val
        self._host_emb, self._host_lab, self._host_val = emb, lab, val
        self.capacity = new_capacity
        self.grow_count += 1

    def _evict_stale(self, below_capacity: int) -> None:
        """Drop compiled executables for tiers strictly below
        ``below_capacity`` — called after a grow publishes, with the
        REPLACED tier as threshold, so the previous tier survives for any
        reader still holding its snapshot while everything older is freed.
        Safe without the write lock: dict mutation is atomic under the GIL
        and an in-flight call already holds its function reference."""
        for key in [k for k in list(self._match_cache) if k[1] < below_capacity]:
            self._match_cache.pop(key, None)
        # An evicted tier is no longer warm: if a swap_from shrinks the
        # gallery and enrolment re-grows THROUGH this tier, prewarm must
        # recompile it rather than skip on a stale membership.
        with self._write_lock:
            self._warmed_capacities = {
                c for c in self._warmed_capacities if c >= below_capacity
            }
        for hook in list(self.evict_hooks):
            try:
                hook(below_capacity)
            except Exception:  # ocvf-lint: disable=swallowed-exception -- eviction is best-effort cache bookkeeping; a raising hook costs warm-cache memory, never correctness, and serving must never die to cleanup
                pass

    def reset(self) -> None:
        with self._write_lock:
            self._epoch += 1  # invalidate any in-flight async grow
            self._pending.clear()
            self._pending_count = 0
            if self.quantizer is not None:
                self.quantizer.invalidate()
            self._host_emb = np.zeros((self.capacity, self.dim), np.float32)
            self._host_lab = np.full((self.capacity,), self.labels_pad, np.int32)
            self._host_val = np.zeros((self.capacity,), bool)
            self._install(self._host_emb, self._host_lab, self._host_val, 0)

    #: grow-worker uploads larger than 2x this are split into chunks of
    #: this many bytes, PACED one at a time: the r5 lifecycle capture
    #: measured a serving call stuck 78 s behind the un-chunked 1 GB
    #: gallery H2D (queue-head blocking on the ~10-30 MB/s tunnel link).
    #: Pacing (await each chunk before queueing the next) bounds any
    #: concurrent serving transfer's wait to ~one chunk.
    CHUNK_UPLOAD_BYTES = 32 * 1024 * 1024

    #: per-CHUNK pacing deadline (round-5 advisor: one shared deadline
    #: meant a mid-upload expiry silently queued every remaining chunk
    #: back-to-back — exactly the head-of-line blocking pacing exists to
    #: prevent, with nothing recorded). 60 s per 32 MB chunk is ~20x the
    #: tunnel's worst measured rate; an expiry is real degradation and is
    #: flagged in ``info["chunk_pacing_timeout"]`` for lifecycle artifacts.
    CHUNK_PACING_TIMEOUT_S = 60.0

    @staticmethod
    def _pace_chunk(buf, deadline: float, cancel=None, info=None) -> bool:
        """Poll ``buf.is_ready()`` until resident, cancelled, or
        ``deadline``; True when the chunk landed (or the wait was
        cancelled), False when pacing gave up — deadline expiry records
        ``info["chunk_pacing_timeout"]`` so the degraded (unpaced) window
        is visible in grow artifacts; a backend without ``is_ready``
        returns False silently (pacing is impossible, not degraded — the
        final residency wait still runs). Transient is_ready errors are
        recorded and polling continues (mirrors ``_await_residency``)."""
        import time as _time

        while True:
            if cancel is not None and cancel():
                return True  # doomed snapshot; publish check discards it
            try:
                if buf.is_ready():
                    return True
            except (AttributeError, NotImplementedError):
                return False  # no is_ready on this backend: cannot pace
            except Exception as e:
                if info is not None and "residency_probe_error" not in info:
                    info["residency_probe_error"] = repr(e)
            if _time.monotonic() >= deadline:
                if info is not None:
                    info["chunk_pacing_timeout"] = True
                return False
            _time.sleep(0.02)

    def _chunked_emb_put(self, emb: np.ndarray, cancel=None,
                         info=None) -> jnp.ndarray:
        """Upload the embedding matrix in paced chunks: device-side zeros
        (no transfer), then donated dynamic_update_slice per chunk, each
        awaited (non-blocking is_ready poll) before the next is queued.
        The device-side copies are HBM-bandwidth cheap; the win is that
        the tunnel link is released between chunks. Each chunk gets its
        OWN pacing deadline (``CHUNK_PACING_TIMEOUT_S``) — a single slow
        chunk degrades only itself, flagged in info — and ``cancel`` is
        sampled inside the poll so a reset aborts within one poll tick.
        The FIRST pacing failure (timeout or no ``is_ready``) stops pacing
        for the remaining chunks: under a hang-mode backend the total
        stall is bounded by one chunk deadline, not chunks * deadline
        (the final residency wait still gates the publish either way)."""
        import time as _time

        cap, dim = emb.shape
        itemsize = self.store_dtype.itemsize
        rows = max(1, self.CHUNK_UPLOAD_BYTES // (dim * itemsize))
        key = (cap, dim, self.store_dtype)
        if getattr(self, "_chunk_jit", None) is None or self._chunk_jit[0] != key:
            zeros = jax.jit(lambda: jnp.zeros((cap, dim), self.store_dtype),
                            out_shardings=self._emb_sharding)
            update = jax.jit(
                lambda b, c, i: jax.lax.dynamic_update_slice(b, c, (i, 0)),
                donate_argnums=0, out_shardings=self._emb_sharding)
            self._chunk_jit = (key, zeros, update)
        _, zeros, update = self._chunk_jit
        buf = zeros()
        pacing = True
        for start in range(0, cap, rows):
            if cancel is not None and cancel():
                return buf  # doomed snapshot; publish check discards it
            # Host-side cast BEFORE the put: the transfer itself must be
            # store_dtype-width (an on-device cast would ship f32 bytes).
            chunk = self._put_emb(emb[start:start + rows])
            buf = update(buf, chunk, np.int32(start))
            if pacing:
                pacing = self._pace_chunk(
                    buf, _time.monotonic() + self.CHUNK_PACING_TIMEOUT_S,
                    cancel=cancel, info=info)
        return buf

    def _build_snapshot(self, emb: np.ndarray, lab: np.ndarray,
                        val: np.ndarray, size: int,
                        chunked: bool = False, cancel=None,
                        info=None, epoch: Optional[int] = None) -> GalleryData:
        """Device-put the arrays WITHOUT publishing (the async grow worker
        waits for residency between build and publish). ``chunked`` (grow
        worker only) paces the big embedding upload so concurrent serving
        transfers are not head-blocked behind it; labels/valid are small
        (5 MB at 1M rows) and always go direct. Chunking is scoped to
        single-device meshes — the serving config this was measured on,
        and the only one where it's a pure win: with tp>1 the
        dynamic-offset update operand cannot be proven shard-local, so
        GSPMD replicates every chunk to all devices (~tp x the transfer
        bytes), while the direct sharded put moves each row exactly once.
        On real pods each host also uploads only its own shards over its
        own link, so the single-link head-blocking this fights is a
        tunneled-single-chip artifact anyway."""
        if (chunked and emb.nbytes > 2 * self.CHUNK_UPLOAD_BYTES
                and len(self.mesh.devices.flat) == 1):
            emb_dev = self._chunked_emb_put(emb, cancel=cancel, info=info)
        else:
            # Host-side cast so the wire carries store_dtype-width bytes.
            emb_dev = self._put_emb(emb)
        return GalleryData(
            embeddings=emb_dev,
            labels=jax.device_put(jnp.asarray(lab), self._lab_sharding),
            valid=jax.device_put(jnp.asarray(val), self._valid_sharding),
            size=size,
            epoch=self._epoch if epoch is None else epoch,
        )

    def _install(self, emb: np.ndarray, lab: np.ndarray, val: np.ndarray, size: int) -> None:
        # Build the full snapshot first, publish with ONE attribute write —
        # serving threads reading self._data never see a partial install.
        self._data = self._build_snapshot(emb, lab, val, size)

    #: bounded wait for the write lock in snapshot(): long enough that a
    #: normal add/grow-splice holding it finishes, short enough that a
    #: hang-mode device transfer stuck INSIDE the locked region (observed
    #: outage shape) cannot wedge a degraded-mode caller on the serving
    #: thread — which would be the exact wedge the resilience layer exists
    #: to prevent.
    SNAPSHOT_LOCK_TIMEOUT_S = 5.0

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
        """Host-mirror copies (no device readback). Prefers the write lock
        (a copy racing a grow splice must not capture a half-written row
        set) but the acquire is BOUNDED: if a hung device_put is holding
        the lock past ``SNAPSHOT_LOCK_TIMEOUT_S``, fall back to lock-free
        copies — best-effort state now beats a guaranteed wedge."""
        acquired = self._write_lock.acquire(timeout=self.SNAPSHOT_LOCK_TIMEOUT_S)
        try:
            return (
                self._host_emb.copy(),
                self._host_lab.copy(),
                self._host_val.copy(),
                self.size,
            )
        finally:
            if acquired:
                self._write_lock.release()

    def load_snapshot(self, emb: np.ndarray, lab: np.ndarray,
                      val: np.ndarray, size: int,
                      embedder_version: Optional[int] = None) -> None:
        """Install host-mirror arrays from a prior ``snapshot()`` as the
        live gallery — the supervisor's last-known-good restore path
        (runtime.resilience.ServiceSupervisor): a crash mid-enrolment must
        not leave a half-written gallery serving. Adopts the snapshot's
        capacity (grows since the checkpoint are rolled back with it) and
        invalidates any in-flight async grow, exactly like ``swap_from``.
        ``embedder_version`` (when given) re-stamps the gallery's version
        along with the whole-set install — the rollout cutover and the
        replica's new-version re-anchor both change version and rows in
        this one atomic publish, so serving can never observe rows from
        one version stamped with another."""
        emb = np.array(emb, np.float32, copy=True)
        if emb.ndim != 2 or emb.shape[1] != self.dim:
            raise ValueError(f"snapshot must be [capacity, {self.dim}], "
                             f"got {emb.shape}")
        with self._write_lock:
            if embedder_version is not None:
                self.embedder_version = int(embedder_version)
            self._epoch += 1  # invalidate any in-flight async grow
            self._pending.clear()
            self._pending_count = 0
            if self.quantizer is not None:
                # Derived state: the snapshot's rows share nothing with
                # the trained cells. Recovery reinstates the quantizer
                # from its wal_seq-keyed sidecar or retrains (see
                # runtime.state_store); until then serving is exact.
                self.quantizer.invalidate()
            self.capacity = emb.shape[0]
            self._host_emb = emb
            self._host_lab = np.array(lab, np.int32, copy=True)
            self._host_val = np.array(val, bool, copy=True)
            self._install(self._host_emb, self._host_lab, self._host_val,
                          int(size))

    def swap_from(self, other: "ShardedGallery") -> None:
        """Atomic-at-Python-level install of another gallery's contents —
        the double-buffered reload path (SURVEY.md §5.3): build ``other``
        off to the side, then swap refs; in-flight match calls keep using
        the old arrays they captured.

        A ``store_dtype`` mismatch is CAST, not rejected: the documented
        retrain -> ``reload_gallery`` handoff builds its staged gallery at
        the trainer's default f32 while serving defaults to bf16
        (round-5 advisor) — the staged host mirrors are f32 truth either
        way, so the device snapshot is simply rebuilt at THIS gallery's
        width (one extra H2D; a reload already pays one). The installed
        snapshot therefore always carries self.store_dtype, so compiled
        cache keys (which carry capacity, not dtype) never alias.

        A ``dim`` mismatch FAILS CLOSED (``EmbeddingDimMismatchError``):
        a donor built by a different-D embedder is a different embedding
        space, and a raw swap would publish scores against rows the query
        embedder cannot compare to. Different-D embedders roll out through
        the staged re-embed path (``runtime.rollout``), never a swap. The
        donor's ``embedder_version`` is adopted atomically with its rows —
        same-version retrain reloads are unaffected (both default 1)."""
        if other.dim != self.dim:
            raise EmbeddingDimMismatchError(
                f"swap_from refused: donor gallery dim {other.dim} != "
                f"serving dim {self.dim}. A different-D embedder must roll "
                f"out via the staged re-embed path (runtime.rollout: "
                f"stage + cutover record + checkpoint), never a raw swap "
                f"— mixing embedding spaces in one served shard set would "
                f"corrupt every published score.")
        recast = other.store_dtype != self.store_dtype
        with self._write_lock:
            self.embedder_version = int(getattr(other, "embedder_version",
                                                self.embedder_version))
            self._epoch += 1  # invalidate any in-flight async grow
            self._pending.clear()
            self._pending_count = 0
            if self.quantizer is not None:
                self.quantizer.invalidate()
            if other.capacity != self.capacity:
                self.capacity = other.capacity
            self._host_emb = other._host_emb
            self._host_lab = other._host_lab
            self._host_val = other._host_val
            if recast:
                # Rebuild at our width from the (always-f32) host mirrors;
                # _install publishes with the single _data write below.
                self._install(self._host_emb, self._host_lab, self._host_val,
                              other.size)
            else:
                # Device-visible swap is the single _data assignment (last,
                # so the host mirrors are already consistent when readers
                # see it) — restamped with THIS gallery's epoch: the donor
                # snapshot carries the donor's counter, and a stale stamp
                # would make every post-swap quantizer publish (stamped
                # with the bumped epoch) fail the _ivf_data pairing check
                # forever, silently pinning serving to the exact path.
                self._data = other._data._replace(epoch=self._epoch)
        # The swapped-in rows need fresh cells: retrain in the background
        # (single-flight); exact matching serves the interim.
        self._poke_quantizer()

    # ---- IVF coarse quantizer (parallel.quantizer) ----

    def attach_quantizer(self, quantizer, mode: str = "auto") -> None:
        """Wire a ``CoarseQuantizer`` as this gallery's shortlist front
        end and select the match mode: ``"auto"`` (exact below
        ``IVF_MIN_CAPACITY``, two-stage above — the serving default),
        ``"ivf"`` (two-stage whenever the quantizer is ready), or
        ``"exact"`` (attached but never consulted). The quantizer is
        derived state: this gallery drives its whole lifecycle (add ->
        incremental assign; reset/load_snapshot/swap_from/grow-splice ->
        invalidate; staleness -> background retrain)."""
        if mode not in ("auto", "ivf", "exact"):
            raise ValueError(f"match mode must be auto|ivf|exact, got {mode!r}")
        quantizer._gallery = self
        self.quantizer = quantizer
        self.match_mode = mode

    def run_locked(self, fn):
        """Run ``fn`` under the write lock — the quantizer's publish path
        (its mutations are serialized by THIS lock, not one of its own,
        so the PR-5 lock-order graph stays a tree rooted here)."""
        with self._write_lock:
            return fn()

    def snapshot_quantizer(self):
        """Atomic (vs. enrolments and retrain publishes) host copy of the
        quantizer's sidecar payload, or None when absent/not ready — the
        checkpoint writer captures this in the same critical section as
        the gallery snapshot so the sidecar can be keyed to the
        checkpoint's ``wal_seq``."""
        if self.quantizer is None:
            return None
        with self._write_lock:
            return self.quantizer.sidecar_payload_locked()

    def _ivf_wanted(self, capacity: Optional[int] = None) -> bool:
        """Would this gallery USE a ready quantizer at ``capacity``?
        (Mode/threshold/mesh gates, ignoring readiness — the build
        trigger needs the answer before any build exists.)"""
        if self.quantizer is None or self.match_mode == "exact":
            return False
        if self.mesh.size != 1:
            return False  # two-stage path is single-device, like pallas
        if self.match_mode == "ivf":
            return True
        return ((self.capacity if capacity is None else capacity)
                >= self.IVF_MIN_CAPACITY)

    def _ivf_enabled(self, capacity: Optional[int] = None) -> bool:
        return self._ivf_wanted(capacity) and self.quantizer.ready

    def _ivf_data(self, data: GalleryData):
        """The quantizer snapshot to pair with the ALREADY-TAKEN gallery
        snapshot ``data``, or None for the exact path — ONE read of
        ``quantizer.data`` so the enabled-check and the arrays can never
        straddle an invalidation, and an epoch cross-check so the two
        non-atomic reads can never pair one row set's gallery arrays
        with another's inverted lists (a swap_from + fast retrain
        between the reads would otherwise score the OLD rows against
        the NEW lists — plausible sims, wrong identities)."""
        if not self._ivf_wanted(data.capacity):
            return None
        ivf = self.quantizer.data  # None when invalidated/not built
        if ivf is None or ivf.gallery_epoch != data.epoch:
            return None
        return ivf

    def _poke_quantizer(self) -> None:
        """Fire the background (re)build when the quantizer is missing-
        but-wanted or stale — the single-flight retrain trigger, called
        after enrolments and swaps (never on the match path)."""
        q = self.quantizer
        if q is None:
            return
        if not q.ready:
            if self._ivf_wanted() and self.size > 0:
                q.maybe_rebuild_async()
        elif q.stale():
            q.maybe_rebuild_async()

    # ---- matching (device-side) ----

    def _pallas_enabled(self, capacity: Optional[int] = None) -> bool:
        """Single-device large-gallery fast path: the streaming pallas
        kernel (ops.pallas_match) never materializes [Q, capacity] in HBM.
        Multi-chip stays on the GSPMD formulation — XLA cannot partition a
        custom call across the tp axis. ``capacity`` overrides the current
        one so prewarm can select for a FUTURE tier."""
        if self._use_pallas_cfg is not None:
            return bool(self._use_pallas_cfg)
        dev = self.mesh.devices.flat[0]
        return (
            self.mesh.size == 1
            and dev.platform == "tpu"
            and (self.capacity if capacity is None else capacity)
            >= self.PALLAS_MIN_CAPACITY
        )

    def match_fn(self, k: int, capacity: Optional[int] = None,
                 use_ivf: Optional[bool] = None):
        """Pure match function with the mode selection applied — shared by
        ``match()`` and the fused pipeline step (``parallel.pipeline``), so
        every caller of the hot op gets the right path, not just direct
        ``gallery.match()`` users. Not jitted here: callers inline it into
        their own jitted graphs. ``capacity`` only influences the
        selection (the fn itself is shape-polymorphic) — prewarm passes
        the future tier's.

        Three tiers of selection:

        - **ivf** (``_ivf_enabled``): two-stage shortlist + exact rerank
          (``ops.ivf_match``). Signature gains a 5th argument —
          ``(q, emb, valid, labels, ivf)`` where ``ivf`` is the
          ``IVFDeviceData`` snapshot from ``_ivf_data`` — because the
          quantizer arrays must flow as jit ARGUMENTS (an incremental
          assignment publishes new arrays; a closure would freeze them).
          Callers branch on ``_ivf_enabled(capacity)`` for the arity and
          PIN their choice via ``use_ivf`` so a concurrent invalidation
          between their check and this call cannot flip the arity under
          them (``None`` re-derives the selection — the legacy shape).
        - **pallas streaming** single-chip exact.
        - **GSPMD global view** multi-chip exact.
        """
        if self._ivf_enabled(capacity) if use_ivf is None else use_ivf:
            from opencv_facerecognizer_tpu.ops.ivf_match import ivf_match_topk

            interpret = self.mesh.devices.flat[0].platform != "tpu"
            labels_pad = self.labels_pad
            nprobe = self.quantizer.nprobe

            def ivf_fn(q, g, valid, labels, ivf):
                # ``g`` rides along unused for signature symmetry with the
                # exact paths (XLA drops it); stage 2 reranks the int8
                # cell-resident rows, ``valid``/``labels`` stay authoritative.
                vals, idx = ivf_match_topk(q, valid, ivf, k=k, nprobe=nprobe,
                                           interpret=interpret)
                return take_labels_with_sentinel(labels, idx, labels_pad), vals, idx

            return ivf_fn
        if self._pallas_enabled(capacity):
            from opencv_facerecognizer_tpu.ops.pallas_match import (
                streaming_match_topk,
            )

            interpret = self.mesh.devices.flat[0].platform != "tpu"
            labels_pad = self.labels_pad

            def fn(q, g, valid, labels):
                vals, idx = streaming_match_topk(
                    q, g, valid, k=k, interpret=interpret
                )
                return take_labels_with_sentinel(labels, idx, labels_pad), vals, idx

            return fn
        return functools.partial(match_global, k=k, mesh=self.mesh)

    def _matcher(self, k: int, data: GalleryData, ivf=None):
        # Keyed by (k, capacity/pallas/ivf shapes) DERIVED FROM THE
        # SNAPSHOTS being matched — a separate self.capacity read could
        # straddle a concurrent grow and pair tier B's key with tier A's
        # arrays (pipeline._step_key has the same rule). A grow changes
        # the static gallery shape, but the old tier's compiled matcher
        # stays valid for any in-flight readers and the new tier gets its
        # own entry (eviction in _evict_stale, not clear() — prewarmed
        # entries survive the swap). An IVF retrain that changes the list
        # shapes (max_cell/spill growth) lands in a fresh entry the same
        # way; same-shape republishes reuse the compiled matcher, with
        # the new arrays flowing as arguments.
        capacity = data.capacity
        ivf_sig = None if ivf is None else ivf.shape_signature()
        key = (k, capacity, self._pallas_enabled(capacity), ivf_sig)
        fn = self._match_cache.get(key)  # fetch once (evict race)
        if fn is None:
            if ivf is not None:
                # A retrain that changed the list shapes orphaned the
                # previous signature's executable at this (k, capacity):
                # purge it, or every staleness retrain leaks a compiled
                # matcher for the process lifetime (capacity-threshold
                # eviction never sees same-capacity signature churn).
                # In-flight calls already hold their function references.
                for stale in [k2 for k2 in list(self._match_cache)
                              if k2[:3] == key[:3]
                              and k2[3] not in (None, ivf_sig)]:
                    self._match_cache.pop(stale, None)
                fn = jax.jit(self.match_fn(k, capacity, use_ivf=True))
            elif self._pallas_enabled(capacity):
                fn = jax.jit(self.match_fn(k, capacity, use_ivf=False))
            else:
                fn = jax.jit(
                    self.match_fn(k, capacity, use_ivf=False),
                    in_shardings=(
                        NamedSharding(self.mesh, P(DP_AXIS, None)),
                        self._emb_sharding,
                        self._valid_sharding,
                        self._lab_sharding,
                    ),
                )
            self._match_cache[key] = fn
        return fn

    def match(self, queries: jnp.ndarray, k: int = 1):
        """[Q, D] L2-normalized queries -> (labels [Q, k], cosine sims [Q, k],
        row indices [Q, k]); Q must divide by the dp axis size."""
        queries = jnp.asarray(queries, jnp.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(f"queries must be [Q, {self.dim}], got {queries.shape}")
        dp = self.mesh.shape[DP_AXIS]
        if queries.shape[0] % dp:
            raise ValueError(f"query count {queries.shape[0]} not divisible by dp={dp}")
        data = self._data  # one snapshot read; never mix fields across writes
        ivf = self._ivf_data(data)  # one epoch-checked quantizer read
        if ivf is not None:
            return self._matcher(int(k), data, ivf)(
                queries, data.embeddings, data.valid, data.labels, ivf)
        return self._matcher(int(k), data)(
            queries, data.embeddings, data.valid, data.labels)
