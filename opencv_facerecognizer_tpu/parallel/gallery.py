"""Sharded enrolled gallery: the TP axis (BASELINE.json:5: "NearestNeighbor
.predict becomes a sharded cosine-similarity matmul against the enrolled
gallery held in TPU HBM").

Design:
- Fixed ``capacity`` (static shapes; XLA recompiles nothing as people
  enroll). Rows beyond ``size`` are invalid and masked to -inf similarity.
- Embeddings live sharded row-wise over the ``tp`` mesh axis; each chip
  computes a [Q, C/tp] bf16 similarity block on its MXU against its HBM
  shard, takes a local top-k, then one small ``all_gather`` of [Q, k]
  candidates per chip merges to the global top-k — the classic
  sharded-matmul + argmax-reduction pattern (SURVEY.md §2.3 TP row).
  Collective traffic is O(Q * k * tp), never O(Q * capacity).
- Labels are tiny ([capacity] int32), so they stay replicated.
- Queries are sharded over ``dp`` and replicated over ``tp``; outputs come
  back sharded over ``dp``.
- Enrolment writes and the double-buffered atomic swap (``runtime``'s
  model-reload-without-drop, SURVEY.md §5.3) happen host-side via
  ``jax.device_put`` with the same shardings.
"""

from __future__ import annotations

import functools
import threading
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS

NEG_INF = jnp.float32(-1e30)


def take_labels_with_sentinel(labels, idx, labels_pad: int):
    """Gather labels for top-k indices, mapping sentinel ``idx == -1`` slots
    (a shard/gallery with fewer than k valid rows) to the pad label — a
    clamped/wrapped gather would pair a real subject's label with the
    -1e30 sentinel sim."""
    return jnp.where(
        idx < 0,
        jnp.int32(labels_pad),
        jnp.take(labels, jnp.maximum(idx, 0)),
    )


def match_global(q, g, valid, labels, *, k: int, mesh: Mesh):
    """Global-view sharded match: the GSPMD formulation.

    Written on full arrays with sharding *annotations* instead of shard_map
    (pick a mesh, annotate, let XLA insert the collectives): the similarity
    matmul is computed shard-local (g row-sharded over tp -> sims
    column-sharded), then a two-phase top-k — phase 1 per tp chunk (local,
    no comms), phase 2 over the tp*k gathered candidates — keeps collective
    traffic O(Q * k * tp) instead of all-gathering [Q, capacity].

    Chosen over shard_map for a concrete reason: on the axon PJRT backend a
    shard_map dispatch costs ~125 ms even on a 1x1 mesh (measured), while
    jit-with-shardings compiles to the exact same local compute and runs in
    ~0.06 ms single-chip.

    q [Q, D]; g [C, D] sharded P(tp, None); valid [C]; labels [C].
    Returns (labels [Q, k], sims [Q, k], gallery indices [Q, k]).
    """
    tp = mesh.shape[TP_AXIS]
    cap = g.shape[0]
    chunk = cap // tp
    # MXU block: bf16 operands, f32 accumulation.
    sims = jax.lax.dot_general(
        q.astype(jnp.bfloat16),
        g.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Q, C]
    sims = jnp.where(valid[None, :], sims, NEG_INF)
    qn = sims.shape[0]
    if tp == 1:
        # Singleton tp: the two-phase split is identical math but the
        # reshape + sharding constraint break XLA's matmul->top_k fusion
        # (measured on v5e: 2.40 vs 1.00 ms/batch for the whole fused
        # serving step at 16k rows) — take the direct top_k.
        top_vals, top_gidx = jax.lax.top_k(sims, min(k, cap))
        return jnp.take(labels, top_gidx), top_vals, top_gidx
    # Phase 1: per-chunk top-k, chunk == tp shard (the constraint pins the
    # reshape to be shard-local).
    s3 = sims.reshape(qn, tp, chunk)
    s3 = jax.lax.with_sharding_constraint(
        s3, NamedSharding(mesh, P(DP_AXIS, TP_AXIS, None))
    )
    local_k = min(k, chunk)
    vals, idx = jax.lax.top_k(s3, local_k)  # [Q, tp, local_k]
    gidx = idx + (jnp.arange(tp, dtype=jnp.int32) * chunk)[None, :, None]
    # Phase 2: merge the tp*local_k candidates (tiny; XLA gathers these).
    vals2 = vals.reshape(qn, tp * local_k)
    gidx2 = gidx.reshape(qn, tp * local_k)
    out_k = min(k, tp * local_k)
    top_vals, pos = jax.lax.top_k(vals2, out_k)
    top_gidx = jnp.take_along_axis(gidx2, pos, axis=1)
    top_labels = jnp.take(labels, top_gidx)
    return top_labels, top_vals, top_gidx


def match_pod_pallas(q, g, valid, labels, *, k: int, mesh: Mesh,
                     interpret: bool = False, labels_pad: int = -1):
    """Pod-scale matcher: ``shard_map`` over tp, pallas streaming kernel
    per shard, collective merge of the tiny candidate sets.

    Each chip streams its [capacity/tp, D] gallery shard through
    ``ops.pallas_match.streaming_match_topk`` (local [Q, k] top-k, no
    [Q, capacity/tp] materialization), then one ``all_gather`` over tp of
    [Q, k] values+indices — O(Q * k * tp) ICI traffic — and a final
    ``lax.top_k`` merge on every chip. This is the multi-chip form of the
    pallas fast path: GSPMD cannot partition a custom call, so the shard
    decomposition is written explicitly here.

    Not the serving default on this machine: the axon tunnel charges
    ~125 ms per shard_map dispatch (measured — see ``match_global``),
    which buries the kernel win. On a real pod slice, dispatch is normal
    and this path pairs the kernel's HBM savings with tp scaling; it is
    CPU-mesh tested in interpret mode either way.

    Shapes/shardings: q [Q, D] dp-sharded; g [C, D] tp row-sharded;
    valid [C] tp-sharded; labels [C] replicated. Returns the same
    (labels [Q, k], sims [Q, k], gallery indices [Q, k]) as match_global.
    """
    from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk

    tp = mesh.shape[TP_AXIS]
    chunk = g.shape[0] // tp

    def shard_body(q_l, g_l, valid_l, labels_l):
        vals, idx = streaming_match_topk(
            q_l, g_l, valid_l, k=min(k, chunk), interpret=interpret
        )
        offset = jax.lax.axis_index(TP_AXIS).astype(jnp.int32) * chunk
        # A shard with fewer valid rows than k emits sentinel -1 indices;
        # keep them -1 instead of offsetting into a neighbor shard's rows.
        idx = jnp.where(idx < 0, -1, idx + offset)
        # One tiled gather each -> [Q, tp*local_k] candidates on every chip.
        cand_v = jax.lax.all_gather(vals, TP_AXIS, axis=1, tiled=True)
        cand_i = jax.lax.all_gather(idx, TP_AXIS, axis=1, tiled=True)
        out_k = min(k, cand_v.shape[1])
        top_v, pos = jax.lax.top_k(cand_v, out_k)
        top_i = jnp.take_along_axis(cand_i, pos, axis=1)
        return take_labels_with_sentinel(labels_l, top_i, labels_pad), top_v, top_i

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(DP_AXIS, None), P(TP_AXIS, None), P(TP_AXIS), P()),
        out_specs=(P(DP_AXIS, None), P(DP_AXIS, None), P(DP_AXIS, None)),
        check_vma=False,
    )(q, g, valid, labels)


class GalleryData(NamedTuple):
    """One immutable snapshot of the device-visible gallery state.

    Reader side of the concurrency story: all reads go through a single
    ``self._data`` attribute load (atomic at Python level), so a reader can
    never observe a mixed snapshot (e.g. new valid mask against old
    embeddings). Writer side: ``add``/``reset``/``swap_from`` serialize on
    an internal lock, so concurrent enrolments can't both claim the same
    rows."""

    embeddings: jnp.ndarray  # [capacity, dim], P(tp, None)
    labels: jnp.ndarray  # [capacity], replicated
    valid: jnp.ndarray  # [capacity], P(tp)
    size: int


class ShardedGallery:
    """Enrolled gallery of L2-normalized embeddings, row-sharded over tp."""

    #: capacity above which the pallas streaming kernel beats the XLA
    #: materialize+top_k path on real hardware (measured on v5e: 1.08x at
    #: 131k rows, 1.73x at 1M; parity/noise at 16k).
    PALLAS_MIN_CAPACITY = 65536

    def __init__(
        self,
        capacity: int,
        dim: int,
        mesh: Mesh,
        labels_pad: int = -1,
        use_pallas: Optional[bool] = None,
    ):
        self.mesh = mesh
        self._use_pallas_cfg = use_pallas
        tp = mesh.shape[TP_AXIS]
        # Round capacity up so every tp shard is equal (static shapes).
        self.capacity = int(np.ceil(capacity / tp) * tp)
        self.dim = int(dim)
        self.labels_pad = labels_pad
        self._emb_sharding = NamedSharding(mesh, P(TP_AXIS, None))
        self._lab_sharding = NamedSharding(mesh, P())
        self._valid_sharding = NamedSharding(mesh, P(TP_AXIS))
        self._host_emb = np.zeros((self.capacity, dim), np.float32)
        self._host_lab = np.full((self.capacity,), labels_pad, np.int32)
        self._host_val = np.zeros((self.capacity,), bool)
        self._write_lock = threading.Lock()
        self.grow_count = 0
        self._data = GalleryData(
            embeddings=jax.device_put(
                jnp.zeros((self.capacity, dim), jnp.float32), self._emb_sharding
            ),
            labels=jax.device_put(
                jnp.full((self.capacity,), labels_pad, jnp.int32), self._lab_sharding
            ),
            valid=jax.device_put(
                jnp.zeros((self.capacity,), bool), self._valid_sharding
            ),
            size=0,
        )
        self._match_cache = {}

    # Single-attribute snapshot: the only device-state read path.
    @property
    def data(self) -> GalleryData:
        return self._data

    @property
    def embeddings(self) -> jnp.ndarray:
        return self._data.embeddings

    @property
    def labels(self) -> jnp.ndarray:
        return self._data.labels

    @property
    def valid(self) -> jnp.ndarray:
        return self._data.valid

    @property
    def size(self) -> int:
        return self._data.size

    # ---- enrolment (host-side; serving never blocks on these) ----

    def add(self, embeddings: np.ndarray, labels: np.ndarray) -> None:
        """Append L2-normalized rows, auto-growing on overflow.

        Growth doubles capacity (tp-aligned) and installs the bigger
        arrays — the same double-buffered install as ``swap_from``, so
        serving threads keep matching against the old snapshot until the
        new one is published. The static-shape change means the matcher
        (and the fused pipeline step) recompile once on the next call;
        ``grow_count`` exposes how often that happened so operators can
        pre-size ``capacity`` instead (a mid-serving XLA compile stalls
        that batch by seconds on real hardware).
        """
        embeddings = np.asarray(embeddings, np.float32)
        embeddings = embeddings / np.maximum(
            np.linalg.norm(embeddings, axis=-1, keepdims=True), 1e-12
        )
        n = embeddings.shape[0]
        with self._write_lock:
            size = self.size
            if size + n > self.capacity:
                self._grow_locked(size + n)
            # Host mirrors are the source of truth for enrolment: a device
            # readback here would trigger the axon backend's sync-poll mode
            # (see module docstring of runtime.recognizer).
            self._host_emb[size : size + n] = embeddings
            self._host_lab[size : size + n] = np.asarray(labels, np.int32)
            self._host_val[size : size + n] = True
            self._install(self._host_emb, self._host_lab, self._host_val, size + n)

    def _grow_locked(self, needed: int) -> None:
        """Double capacity (tp-aligned) until ``needed`` rows fit; caller
        holds the write lock."""
        tp = self.mesh.shape[TP_AXIS]
        new_capacity = max(self.capacity, 1)
        while new_capacity < needed:
            new_capacity *= 2
        new_capacity = int(np.ceil(new_capacity / tp) * tp)
        emb = np.zeros((new_capacity, self.dim), np.float32)
        lab = np.full((new_capacity,), self.labels_pad, np.int32)
        val = np.zeros((new_capacity,), bool)
        emb[: self.capacity] = self._host_emb
        lab[: self.capacity] = self._host_lab
        val[: self.capacity] = self._host_val
        self._host_emb, self._host_lab, self._host_val = emb, lab, val
        self.capacity = new_capacity
        self._match_cache.clear()  # compiled for the old static shape
        self.grow_count += 1

    def reset(self) -> None:
        with self._write_lock:
            self._host_emb = np.zeros((self.capacity, self.dim), np.float32)
            self._host_lab = np.full((self.capacity,), self.labels_pad, np.int32)
            self._host_val = np.zeros((self.capacity,), bool)
            self._install(self._host_emb, self._host_lab, self._host_val, 0)

    def _install(self, emb: np.ndarray, lab: np.ndarray, val: np.ndarray, size: int) -> None:
        # Build the full snapshot first, publish with ONE attribute write —
        # serving threads reading self._data never see a partial install.
        self._data = GalleryData(
            embeddings=jax.device_put(jnp.asarray(emb), self._emb_sharding),
            labels=jax.device_put(jnp.asarray(lab), self._lab_sharding),
            valid=jax.device_put(jnp.asarray(val), self._valid_sharding),
            size=size,
        )

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
        """Host-mirror copies (no device readback)."""
        return (
            self._host_emb.copy(),
            self._host_lab.copy(),
            self._host_val.copy(),
            self.size,
        )

    def swap_from(self, other: "ShardedGallery") -> None:
        """Atomic-at-Python-level install of another gallery's contents —
        the double-buffered reload path (SURVEY.md §5.3): build ``other``
        off to the side, then swap refs; in-flight match calls keep using
        the old arrays they captured."""
        if other.dim != self.dim:
            raise ValueError(f"dim mismatch: {other.dim} != {self.dim}")
        with self._write_lock:
            if other.capacity != self.capacity:
                # Different static shape: cached matchers no longer apply.
                self.capacity = other.capacity
                self._match_cache.clear()
            self._host_emb = other._host_emb
            self._host_lab = other._host_lab
            self._host_val = other._host_val
            # Device-visible swap is the single _data assignment (last, so
            # the host mirrors are already consistent when readers see it).
            self._data = other._data

    # ---- matching (device-side) ----

    def _pallas_enabled(self) -> bool:
        """Single-device large-gallery fast path: the streaming pallas
        kernel (ops.pallas_match) never materializes [Q, capacity] in HBM.
        Multi-chip stays on the GSPMD formulation — XLA cannot partition a
        custom call across the tp axis."""
        if self._use_pallas_cfg is not None:
            return bool(self._use_pallas_cfg)
        dev = self.mesh.devices.flat[0]
        return (
            self.mesh.size == 1
            and dev.platform == "tpu"
            and self.capacity >= self.PALLAS_MIN_CAPACITY
        )

    def match_fn(self, k: int):
        """Pure ``(q, emb, valid, labels) -> (labels, sims, idx)`` match
        function with the pallas-vs-GSPMD selection applied — shared by
        ``match()`` and the fused pipeline step (``parallel.pipeline``), so
        every caller of the hot op gets the streaming fast path, not just
        direct ``gallery.match()`` users. Not jitted here: callers inline
        it into their own jitted graphs."""
        if self._pallas_enabled():
            from opencv_facerecognizer_tpu.ops.pallas_match import (
                streaming_match_topk,
            )

            interpret = self.mesh.devices.flat[0].platform != "tpu"
            labels_pad = self.labels_pad

            def fn(q, g, valid, labels):
                vals, idx = streaming_match_topk(
                    q, g, valid, k=k, interpret=interpret
                )
                return take_labels_with_sentinel(labels, idx, labels_pad), vals, idx

            return fn
        return functools.partial(match_global, k=k, mesh=self.mesh)

    def _matcher(self, k: int):
        if k not in self._match_cache:
            if self._pallas_enabled():
                fn = jax.jit(self.match_fn(k))
            else:
                fn = jax.jit(
                    self.match_fn(k),
                    in_shardings=(
                        NamedSharding(self.mesh, P(DP_AXIS, None)),
                        self._emb_sharding,
                        self._valid_sharding,
                        self._lab_sharding,
                    ),
                )
            self._match_cache[k] = fn
        return self._match_cache[k]

    def match(self, queries: jnp.ndarray, k: int = 1):
        """[Q, D] L2-normalized queries -> (labels [Q, k], cosine sims [Q, k],
        row indices [Q, k]); Q must divide by the dp axis size."""
        queries = jnp.asarray(queries, jnp.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(f"queries must be [Q, {self.dim}], got {queries.shape}")
        dp = self.mesh.shape[DP_AXIS]
        if queries.shape[0] % dp:
            raise ValueError(f"query count {queries.shape[0]} not divisible by dp={dp}")
        data = self._data  # one snapshot read; never mix fields across writes
        return self._matcher(int(k))(queries, data.embeddings, data.valid, data.labels)
