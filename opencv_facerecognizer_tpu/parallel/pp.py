"""Pipeline parallelism: detect/align and embed/match on disjoint device
subsets (SURVEY.md §2.3 "PP" row — optional in the reference mapping, built
here to complete the parallelism surface).

When to use: the fused single-graph pipeline (``parallel.pipeline``) is the
right default — one chip holds both nets comfortably and XLA fuses across
stages. PP pays off when the stages *can't* share a chip (a much larger
detector/embedder, or a gallery occupying most of HBM) or when stage
specialization beats data parallelism for a fixed chip budget.

TPU-first shape of the design:

- Stage A (detector convs + static-shape decode + matmul-form crop-resize)
  is one jitted graph pinned to ``mesh_a``; stage B (embedder + gallery
  match) is another pinned to the gallery's mesh. Each mesh is an ordinary
  (dp, tp) mesh, so stage B's gallery is still tp-sharded *within* its
  subset — PP composes with the existing axes rather than replacing them.
  Stage B's matcher comes from ``ShardedGallery.match_fn``, so the pallas
  streaming fast path applies under the same conditions as everywhere else.
- The inter-stage hop is a ``jax.device_put`` of the [B, K, fh, fw] crop
  block to stage B's shardings, plus the tiny box/score/valid arrays
  (so every result leaf lands on stage B's mesh and the packed
  single-readback path is one jit) — on hardware these are
  device-to-device ICI transfers, no host round-trip.
- Pipelining needs no threads: JAX dispatch is async, and the two graphs
  occupy disjoint devices, so issuing A(i+1) before draining B(i) overlaps
  them; ``depth=2`` software pipelining falls out of call ordering. The
  driver keeps at most one batch in each stage.
- The gallery stays LIVE: every batch reads ``gallery.data`` (the same
  atomic snapshot discipline as the fused pipeline), so enrolments and
  double-buffered swaps land on the next batch; a capacity grow re-selects
  the matcher and retraces stage B, exactly like
  ``RecognitionPipeline._step_key``.

Correctness contract: identical outputs to
``RecognitionPipeline.recognize_batch`` for the same inputs (tested on the
CPU mesh in tests/test_pp.py).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opencv_facerecognizer_tpu.models import detector as detector_mod
from opencv_facerecognizer_tpu.models import embedder as embedder_mod
from opencv_facerecognizer_tpu.ops import image as image_ops
from opencv_facerecognizer_tpu.parallel.gallery import ShardedGallery
from opencv_facerecognizer_tpu.parallel.mesh import DP_AXIS, TP_AXIS
from opencv_facerecognizer_tpu.parallel.pipeline import (
    RecognitionResult, pack_result,
)


def split_mesh(mesh: Mesh) -> Tuple[Mesh, Mesh]:
    """Split a (dp, tp) mesh into two equal stage meshes along dp.

    dp is the split axis because stage A has no tp dimension (detector
    params are replicated) while stage B may want every tp shard it can
    get; equal halves keep one batch size valid on both stages. Odd dp is
    rejected — unequal halves would need per-stage batch sizes (a 9-frame
    batch cannot dp-shard 2 ways on one half and 1 way on the other).
    """
    devs = mesh.devices
    dp = devs.shape[0]
    if dp < 2 or dp % 2:
        raise ValueError(
            f"PP needs an even dp >= 2 to split equally (got dp={dp}); "
            "build the mesh with make_mesh(dp=2*n) or use the fused "
            "single-mesh pipeline"
        )
    half = dp // 2
    return (Mesh(devs[:half], (DP_AXIS, TP_AXIS)),
            Mesh(devs[half:], (DP_AXIS, TP_AXIS)))


class TwoStagePipeline:
    """Detect/align on ``mesh_a``; embed/match on ``gallery.mesh``."""

    def __init__(
        self,
        detector: detector_mod.CNNFaceDetector,
        embed_net: embedder_mod.FaceEmbedNet,
        embed_params: Dict[str, Any],
        gallery: ShardedGallery,
        mesh_a: Mesh,
        face_size: Tuple[int, int] = (112, 112),
        top_k: int = 1,
    ):
        mesh_b = gallery.mesh
        overlap = (set(d.id for d in mesh_a.devices.flat)
                   & set(d.id for d in mesh_b.devices.flat))
        if overlap:
            raise ValueError(
                f"stage meshes share devices {sorted(overlap)}; PP requires "
                "disjoint subsets (use split_mesh, and build the gallery on "
                "the second half)"
            )
        self.detector = detector
        self.embed_net = embed_net
        # Public, mesh-agnostic copy — RecognizerService's enrolment path
        # runs the embedder host-side batches through embed_net/embed_params
        # exactly as it does with RecognitionPipeline.
        self.embed_params = embed_params
        self.gallery = gallery
        self.face_size = tuple(face_size)
        self.top_k = int(top_k)
        self.mesh_a = mesh_a
        self.mesh_b = mesh_b
        det = detector
        max_faces = det.max_faces

        def stage_a(det_params, frames):
            frames = frames.astype(jnp.float32)  # uint8 fast-transfer path
            outputs = det.net.apply({"params": det_params}, frames)
            boxes, det_scores, valid = detector_mod.decode_detections(
                outputs, max_faces, det.score_threshold, det.iou_threshold
            )
            crops = image_ops.batched_crop_resize(frames, boxes, face_size)
            return boxes, det_scores, valid, crops

        frames_in = NamedSharding(mesh_a, P(DP_AXIS, None, None))
        self._stage_a = jax.jit(stage_a, in_shardings=(None, frames_in))
        # Stage B input shardings for the inter-stage device_put hop.
        self._b_crops = NamedSharding(mesh_b, P(DP_AXIS, None, None, None))
        self._b_repl = NamedSharding(mesh_b, P())
        # Params are static per pipeline: pin each stage's copy to its mesh
        # once. The GALLERY is deliberately not snapshotted here — see
        # _stage_b_fn/_submit_b.
        self._emb_params = jax.device_put(embed_params, self._b_repl)
        self._det_params = jax.device_put(
            detector.params, NamedSharding(mesh_a, P())
        )
        self._b_cache: Dict[Any, Any] = {}
        self._served_crop_shapes = set()
        self._pack = jax.jit(pack_result)  # once: serving hot-loop path
        # Same off-the-serving-path warm contract as RecognitionPipeline:
        # the gallery's grow worker compiles stage B for the target tier
        # before publishing the swap, and stale tiers' executables are
        # dropped after a later grow publishes.
        gallery.prewarm_hooks.append(self.prewarm_capacity)
        gallery.evict_hooks.append(self.evict_below)

    def prewarm_capacity(self, capacity: int) -> None:
        """Compile stage B for a FUTURE gallery capacity (grow-worker
        thread): build the stage-B jit for the target (capacity, pallas)
        key and force its compile with zero-filled scratch arrays at every
        crop shape already served."""
        g = self.gallery
        key = (capacity, g._pallas_enabled(capacity))
        if key in self._b_cache:
            fn = self._b_cache[key]
        else:
            match = g.match_fn(self.top_k, capacity)
            embed_net = self.embed_net
            face_size = self.face_size
            k = self.top_k

            def stage_b(emb_params, g_emb, g_valid, g_labels, crops):
                b, kf = crops.shape[0], crops.shape[1]
                flat = crops.reshape((b * kf, *face_size))
                emb = embed_net.apply(
                    {"params": emb_params},
                    embedder_mod.normalize_faces(flat, face_size),
                )
                labels, sims, _ = match(emb, g_emb, g_valid, g_labels)
                return labels.reshape((b, kf, k)), sims.reshape((b, kf, k))

            fn = self._b_cache[key] = jax.jit(stage_b)
        served_shapes = set(self._served_crop_shapes)
        if not served_shapes:
            return
        # store_dtype, not f32: aval mismatch would nullify the warm
        # (see pipeline.prewarm_capacity).
        scratch_emb = jax.device_put(
            jnp.zeros((capacity, g.dim), g.store_dtype), g._emb_sharding
        )
        scratch_lab = jax.device_put(
            jnp.full((capacity,), g.labels_pad, jnp.int32), g._lab_sharding
        )
        scratch_val = jax.device_put(
            jnp.zeros((capacity,), bool), g._valid_sharding
        )
        for crop_shape in served_shapes:
            crops = jax.device_put(jnp.zeros(crop_shape, jnp.float32),
                                   self._b_crops)
            out = fn(self._emb_params, scratch_emb, scratch_val, scratch_lab,
                     crops)
            jax.block_until_ready(out)

    def _stage_b_fn(self, data):
        """Compiled stage B for the given snapshot's capacity/matcher —
        auto-grow changes both, so key the cache like
        ``RecognitionPipeline._step_key`` does, deriving capacity from the
        SAME GalleryData snapshot the call will feed (a separate
        ``gallery.capacity`` read could straddle a concurrent grow
        install and pair a stale key with new-tier arrays)."""
        capacity = data.capacity
        key = (capacity, self.gallery._pallas_enabled(capacity))
        fn = self._b_cache.get(key)  # fetch once (evict race)
        if fn is None:
            match = self.gallery.match_fn(self.top_k, capacity)
            embed_net = self.embed_net
            face_size = self.face_size
            k = self.top_k

            def stage_b(emb_params, g_emb, g_valid, g_labels, crops):
                b, kf = crops.shape[0], crops.shape[1]
                flat = crops.reshape((b * kf, *face_size))
                emb = embed_net.apply(
                    {"params": emb_params},
                    embedder_mod.normalize_faces(flat, face_size),
                )
                labels, sims, _ = match(emb, g_emb, g_valid, g_labels)
                return labels.reshape((b, kf, k)), sims.reshape((b, kf, k))

            fn = self._b_cache[key] = jax.jit(stage_b)
        return fn

    def evict_below(self, min_capacity: int) -> None:
        """Drop stage-B executables for gallery tiers strictly below
        ``min_capacity`` (see ``ShardedGallery.evict_hooks``)."""
        for key in [k for k in list(self._b_cache) if k[0] < min_capacity]:
            self._b_cache.pop(key, None)

    def _submit_a(self, frames):
        frames = jnp.asarray(frames)
        if frames.dtype != jnp.uint8:  # uint8 rides H2D as-is, cast in-graph
            frames = frames.astype(jnp.float32)
        return self._stage_a(self._det_params, frames)

    def _hop(self, a_out):
        boxes, det_scores, valid, crops = a_out
        # One D2D transfer of the stage boundary to mesh_b's shardings.
        # The per-slot arrays are tiny ([B, K, 4] and smaller); moving them
        # too keeps every result leaf on mesh_b, so the packed single-
        # readback path can fuse them in one jit.
        crops_b = jax.device_put(crops, self._b_crops)
        boxes, det_scores, valid = jax.device_put(
            (boxes, det_scores, valid), self._b_repl
        )
        return boxes, det_scores, valid, crops_b

    def _submit_b(self, hopped):
        boxes, det_scores, valid, crops_b = hopped
        self._served_crop_shapes.add(tuple(crops_b.shape))
        data = self.gallery.data  # one atomic snapshot per batch (live)
        labels, sims = self._stage_b_fn(data)(
            self._emb_params, data.embeddings, data.valid, data.labels,
            crops_b,
        )
        return RecognitionResult(
            boxes=boxes, det_scores=det_scores, valid=valid,
            labels=labels, similarities=sims,
        )

    def recognize_batch(self, frames) -> RecognitionResult:
        """Single-batch convenience path (no overlap)."""
        return self._submit_b(self._hop(self._submit_a(frames)))

    def recognize_batch_packed(self, frames) -> jnp.ndarray:
        """One packed [B, K, 6 + 2k] output array (see
        ``pipeline.pack_result``) — makes PP a drop-in pipeline for
        ``runtime.recognizer.RecognizerService``, whose serving loop does
        exactly one device->host readback per batch."""
        result = self.recognize_batch(frames)
        return self._pack(result)

    def recognize_stream(
        self, frame_batches: Iterable[Any]
    ) -> Iterator[RecognitionResult]:
        """Depth-2 pipelined stream: stage A works on batch i+1 while stage
        B works on batch i — overlap comes from async dispatch onto
        disjoint devices, not from host threads."""
        in_flight = None
        for frames in frame_batches:
            hopped = self._hop(self._submit_a(frames))
            if in_flight is not None:
                yield in_flight
            in_flight = self._submit_b(hopped)
        if in_flight is not None:
            yield in_flight
