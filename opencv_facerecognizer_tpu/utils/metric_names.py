"""Canonical registry of every metric name on the shared ``Metrics``
surface.

The chaos soaks, the admission ledger, the overload bench and the tests
all compare counters *by string name* across a dozen files; a single typo
silently breaks an accounting invariant with no error anywhere.  Every
``incr``/``observe``/``set_gauge`` (and read-side ``counter``/
``percentile``/``counters_with_prefix``) call must use a constant from
this module, or a literal whose value appears here — enforced statically
by ``python -m tools.ocvf_lint`` (rule ``metrics-registry``).

Constants ending in ``_PREFIX`` name families whose suffix is dynamic
(``frames_rejected_<reason>``); the prefix itself is what gets validated.

Adding a metric: add the constant here first, then use it at the call
site.  Never inline a new name string at a call site.
"""

# ---- serving loop: frame lifecycle counters -------------------------------
FRAMES_ADMITTED = "frames_admitted"
FRAMES_COMPLETED = "frames_completed"
FRAMES_PROCESSED = "frames_processed"
FRAMES_MALFORMED = "frames_malformed"
FRAMES_DROPPED = "frames_dropped"
FRAMES_DROPPED_BROWNOUT = "frames_dropped_brownout"
FRAMES_DROPPED_CRASHED = "frames_dropped_crashed"
FRAMES_FAILED = "frames_failed"
FRAMES_DEAD_LETTERED = "frames_dead_lettered"
FACES_FOUND = "faces_found"
SUBJECTS_ENROLLED = "subjects_enrolled"
GALLERY_GROWN = "gallery_grown"

# ---- serving loop: batch counters -----------------------------------------
BATCHES_DISPATCHED = "batches_dispatched"
BATCHES_BUCKETED = "batches_bucketed"
BATCHES_FAILED = "batches_failed"
BATCHES_DEAD_LETTERED = "batches_dead_lettered"
LOOP_CRASHES = "loop_crashes"
DISPATCH_FAILURES = "dispatch_failures"
DISPATCH_RETRIES = "dispatch_retries"
READBACK_ERRORS = "readback_errors"
CPU_FALLBACKS = "cpu_fallbacks"
DEGRADED_TRANSITIONS = "degraded_transitions"
DEGRADED_RECOVERIES = "degraded_recoveries"

# ---- serving loop: latency windows (observe) ------------------------------
WARMUP = "warmup"
QUEUE_WAIT = "queue_wait"
DISPATCH = "dispatch"
PUBLISH = "publish"
BATCH_LATENCY = "batch_latency"
READY_WAIT = "ready_wait"
#: per-frame end-to-end latency (batcher enqueue -> result publish), the
#: SLO layer's headline histogram family; the ``_interactive`` window is
#: the same observation restricted to interactive-priority frames.
E2E_LATENCY = "e2e_latency"
E2E_LATENCY_INTERACTIVE = "e2e_latency_interactive"

# ---- cascade early-exit detection (models.cascade + the serving gate) ------
#: terminal admission-ledger status for frames the stage-1 cascade scored
#: face-free: published with an empty face list, never dispatched to the
#: full detect->crop->embed->match step. NOT a drop — the ledger invariant
#: is ``admitted == completed + completed_empty + Σ drops``.
FRAMES_COMPLETED_EMPTY = "frames_completed_empty"
#: frames the stage-1 pass scored (rejected + passed), and whole batches
#: that exited at the cascade (zero survivors — no stage-2 dispatch).
CASCADE_FRAMES_SCORED = "cascade_frames_scored"
CASCADE_BATCH_EXITS = "cascade_batch_exits"
#: a stage-1 scoring pass raised: the batch fails OPEN to the full
#: detector (availability beats the early-exit win), counted loudly.
CASCADE_ERRORS = "cascade_errors"
#: host wall of one stage-1 pass incl. its tiny [B] readback (observe).
CASCADE_SCORE = "cascade_score"
#: first-class /prom gauges: cumulative reject/pass fractions of scored
#: frames, and the EFFECTIVE operating threshold (incl. the brownout
#: tightening notch) the last batch was gated at.
CASCADE_REJECT_RATE = "cascade_reject_rate"
CASCADE_PASS_RATE = "cascade_pass_rate"
CASCADE_THRESHOLD = "cascade_threshold"

# ---- temporal identity cache (runtime.tracker, ISSUE 17) -------------------
#: terminal admission-ledger status for frames served FROM the track
#: cache: published with the cached identities (``exit: track_cache``),
#: never dispatched — a sibling of ``completed``/``completed_empty``, not
#: a drop. The ledger invariant is ``admitted == completed +
#: completed_empty + completed_cached + Σ drops``.
FRAMES_COMPLETED_CACHED = "frames_completed_cached"
#: cache consults (one per tracked frame entering _serve_one) and the
#: frames they answered from the cache.
TRACK_LOOKUPS = "track_lookups"
TRACK_CACHE_HITS = "track_cache_hits"
#: /prom gauges: cumulative hit fraction of lookups, and live tracks.
TRACK_CACHE_HIT_RATE = "track_cache_hit_rate"
TRACKS_LIVE = "tracks_live"
TRACKS_CREATED = "tracks_created"
TRACKS_CONFIRMED = "tracks_confirmed"
#: full verifies forced by the schedule (every reverify_frames) or by
#: appearance drift under a live track.
TRACK_REVERIFIES = "track_reverifies"
#: per-reason flush family ``track_flushes_<identity|ambiguity|version|
#: lost|reset>`` (see runtime/tracker.py module docstring).
TRACK_FLUSHES_PREFIX = "track_flushes_"
#: whole batches that settled entirely from the cache (no dispatch), and
#: tracker call failures (fail OPEN: the frame takes the full path).
TRACK_BATCH_EXITS = "track_batch_exits"
TRACK_ERRORS = "track_errors"

# ---- admission / brownout (overload layer) --------------------------------
#: per-reason rejection family: ``frames_rejected_<reason>``
FRAMES_REJECTED_PREFIX = "frames_rejected_"
BROWNOUT_LEVEL = "brownout_level"
BROWNOUT_TRANSITIONS = "brownout_transitions"
BROWNOUT_RECOVERIES = "brownout_recoveries"

# ---- batcher ---------------------------------------------------------------
BATCHER_FRAMES_OFFERED = "batcher_frames_offered"
BATCHER_FRAMES_BATCHED = "batcher_frames_batched"
#: per-reason drop family: ``batcher_dropped_<reason>``
BATCHER_DROPPED_PREFIX = "batcher_dropped_"
BATCHER_DROPPED_MALFORMED = "batcher_dropped_malformed"
BATCHER_DROPPED_CLOSED = "batcher_dropped_closed"
BATCHER_DROPPED_OVERFLOW = "batcher_dropped_overflow"
BATCHER_DROPPED_STALE = "batcher_dropped_stale"
BATCHER_BATCHES_SIZE = "batcher_batches_size"
BATCHER_BATCHES_DEADLINE = "batcher_batches_deadline"
BATCHER_BUFFER_REUSE = "batcher_buffer_reuse"
BATCHER_FLUSH_DEADLINE_MS = "batcher_flush_deadline_ms"

# ---- ingest pipeline (runtime.ingest) ---------------------------------------
#: staging-ring buffer allocations: the per-rung preallocation at
#: construction plus outage heals (a forfeited buffer replaced after a
#: dead-letter). Steady-state serving must never move this counter — the
#: zero-alloc assertion the ingest tests pin.
INGEST_STAGING_ALLOCS = "ingest_staging_allocs"
INGEST_STAGING_REUSE = "ingest_staging_reuse"
#: an acquire found every fitting rung empty (ring exhausted): the batch
#: stays queued and admission backpressure (reason ``staging``) sheds new
#: intake — never an allocation.
INGEST_STAGING_EXHAUSTED = "ingest_staging_exhausted"
#: buffers the service told the ring it will never get back (dead-letter /
#: crash paths keep the staging array out of circulation because the
#: backend's async H2D read may still be pending).
INGEST_STAGING_FORFEITS = "ingest_staging_forfeits"
INGEST_STAGING_FREE = "ingest_staging_free"
#: host-side device-upload enqueue time (seconds, observe) and the bytes
#: shipped across H2D — bytes/frame in uint8 mode is the 4x story.
INGEST_UPLOAD = "ingest_upload"
INGEST_UPLOAD_BYTES = "ingest_upload_bytes"

# ---- compressed-frame intake: decode worker pool (runtime.ingest) -----------
DECODE_LATENCY = "decode_latency"
DECODE_QUEUE_DEPTH = "decode_queue_depth"
DECODE_FRAMES = "decode_frames"
DECODE_ERRORS = "decode_errors"
#: admission-ledger drop bucket: an ADMITTED compressed frame that never
#: became a pixel frame (corrupt/truncated payload, or decode backlog
#: overflow) — journaled with reason ``decode_error``/``decode_backlog``.
FRAMES_DROPPED_DECODE = "frames_dropped_decode"

# ---- connectors ------------------------------------------------------------
CONNECTOR_MALFORMED_LINES = "connector_malformed_lines"
CONNECTOR_PEER_DISCONNECTS = "connector_peer_disconnects"
CONNECTOR_RECONNECTS = "connector_reconnects"
CONNECTOR_RECONNECT_FAILURES = "connector_reconnect_failures"
CONNECTOR_STALLED_CLIENTS_DROPPED = "connector_stalled_clients_dropped"

# ---- transport fault boundary (runtime.faults, ISSUE 16) -------------------
#: per-kind family of transport faults a send/recv crossing actually
#: enacted: ``transport_fault_<partition|slow|drop|duplicate|reorder|
#: half_open>``.  Counted by the CALLER that crossed the boundary (router
#: forward/fan-in, socket connector send/recv), so the metrics surface and
#: the injector's own ``injected`` ledger can be cross-checked exactly.
TRANSPORT_FAULTS_PREFIX = "transport_fault_"

# ---- idempotent routing: frame-id dedup (ISSUE 16) -------------------------
#: duplicate deliveries of an already-admitted frame id, refused at
#: replica intake BEFORE admission — like ``frames_rejected_<reason>``
#: these sit OUTSIDE the admission ledger by design, so
#: ``admitted == completed + completed_empty + Σ drops`` holds exactly
#: under duplication, retries, and failover re-sends.
FRAMES_DEDUPED = "frames_deduped"
#: duplicate results for one frame id swallowed at the router's fan-in
#: (the second copy of a hedged or duplicated frame's result) — the
#: guarantee that a result is never double-published upstream.
ROUTER_RESULTS_DEDUPED = "router_results_deduped"

# ---- link supervision (runtime.replication.TopicRouter, ISSUE 16) ----------
#: application-level heartbeats: pings the router sent down each replica
#: link, and pongs that made it back through the transport boundary.
LINK_HEARTBEATS_SENT = "link_heartbeats_sent"
LINK_HEARTBEATS_RECEIVED = "link_heartbeats_received"
#: per-replica link gauge family ``link_state_<replica>``: 1 = pong seen
#: within the deadline, 0 = link down (partitioned / half-open — the
#: replica is excluded from rendezvous until the link heals).
LINK_STATE_PREFIX = "link_state_"
#: link up->down / down->up transitions, and the current count of down
#: links (gauge — the ``link_health`` SLO objective's numerator).
LINK_FAILURES = "link_failures"
LINK_RECOVERIES = "link_recoveries"
LINKS_DOWN = "links_down"

# ---- dead-letter journal ---------------------------------------------------
JOURNAL_ERRORS = "journal_errors"
JOURNAL_RECORDS = "journal_records"
JOURNAL_FRAMES = "journal_frames"
#: a pre-existing journal file whose last line had no terminating newline
#: (an ENOSPC/crash-torn append from a previous process): sealed at open
#: so the remnant stays one isolated unparseable line — never the prefix
#: of a new acknowledged record.
JOURNAL_TORN_TAILS = "journal_torn_tails"
#: records deliberately NOT written because durability is degraded (the
#: non-critical-sink shed posture): exact accounting, not a silent
#: best-effort swallow.
JOURNAL_SHED = "journal_shed"

# ---- durable state: checkpoints --------------------------------------------
CHECKPOINTS_WRITTEN = "checkpoints_written"
CHECKPOINTS_CORRUPT = "checkpoints_corrupt"
CHECKPOINTS_VERSION_SKIPPED = "checkpoints_version_skipped"
CHECKPOINT_READ_ERRORS = "checkpoint_read_errors"
CHECKPOINT_FAILURES = "checkpoint_failures"
CHECKPOINTS_SKIPPED_INFLIGHT = "checkpoints_skipped_inflight"
CHECKPOINTS_DEFERRED_PENDING = "checkpoints_deferred_pending"
#: retention-sweep removals (stale tmp files, pruned checkpoints,
#: quarantine excess) that failed with an OSError — previously a silent
#: ``pass``; a GC that stops GC-ing on a sick disk must be visible.
CHECKPOINT_GC_ERRORS = "checkpoint_gc_errors"

# ---- durable state: enrollment WAL -----------------------------------------
WAL_APPENDS = "wal_appends"
WAL_ROWS_APPENDED = "wal_rows_appended"
WAL_ABORTS = "wal_aborts"
WAL_CORRUPT_RECORDS = "wal_corrupt_records"
WAL_SKIPPED_RECORDS = "wal_skipped_records"
WAL_REPLAYED_RECORDS = "wal_replayed_records"
WAL_REPLAYED_ROWS = "wal_replayed_rows"
WAL_TAIL_REPLAYED_ROWS = "wal_tail_replayed_rows"
WAL_TORN_TAILS_SEALED = "wal_torn_tails_sealed"
WAL_OVER_BYTES = "wal_over_bytes"
WAL_ROWS = "wal_rows"
#: strict WAL appends that FAILED with an OSError (ENOSPC/EIO — the
#: enrollment was refused, never acknowledged): the signal the
#: degraded-durability state machine counts toward its flip.
WAL_APPEND_ERRORS = "wal_append_errors"
STATE_RECOVERIES = "state_recoveries"

# ---- degraded-durability state machine (runtime.resilience, ISSUE 15) ------
#: gauge: 0 = durability armed (WAL appends acknowledged durable),
#: 1 = durability_degraded (sustained storage failure — enrollments are
#: refused closed, serving/read traffic continues, non-critical sinks
#: shed). Exported on /prom; /health carries the disk objective.
DURABILITY_STATE = "durability_state"
DURABILITY_DEGRADED_TRANSITIONS = "durability_degraded_transitions"
#: degraded -> armed recoveries (the background probe's tmp write+fsync
#: succeeded and re-armed acknowledged durability).
DURABILITY_REARMS = "durability_rearms"
DURABILITY_PROBES = "durability_probes"
DURABILITY_PROBE_FAILURES = "durability_probe_failures"
#: enroll commands / finished enrolments refused CLOSED while degraded
#: (explicit ``durability_degraded`` status — the ack never lies).
ENROLLMENTS_REFUSED_DEGRADED = "enrollments_refused_degraded"
#: split-brain safety (ISSUE 16): the monitor's lease-directory
#: reachability check failed — a writer partitioned from its own lease
#: volume must flip durability-degraded rather than ack enrollments the
#: fleet can't see.
DURABILITY_LEASE_CHECK_FAILURES = "durability_lease_check_failures"

# ---- disk-pressure watermarks (runtime.resilience, ISSUE 15) ---------------
#: statvfs free bytes on the state volume (gauge, refreshed by the
#: durability monitor's tick) and the derived pressure state: 0 = ok,
#: 1 = warn (below the low watermark — preemptive WAL compaction +
#: retention shrink fired), 2 = critical (the degraded flip pre-empted
#: ENOSPC).
DISK_FREE_BYTES = "disk_free_bytes"
DISK_PRESSURE_STATE = "disk_pressure_state"
#: warn-watermark actions: forced checkpoint-compactions of the WAL, and
#: retention shrinks (checkpoint keep / flight-dump keep / journal
#: backups tightened to their floor).
DISK_PRESSURE_COMPACTIONS = "disk_pressure_compactions"
DISK_PRESSURE_RETENTION_SHRINKS = "disk_pressure_retention_shrinks"

# ---- IVF coarse quantizer (parallel.quantizer / ops.ivf_match) -------------
IVF_BUILDS = "ivf_builds"
IVF_BUILD_FAILURES = "ivf_build_failures"
IVF_RETRAINS_SKIPPED_INFLIGHT = "ivf_retrains_skipped_inflight"
IVF_INVALIDATIONS = "ivf_invalidations"
IVF_INCREMENTAL_ROWS = "ivf_incremental_rows"
IVF_SPILL_ROWS = "ivf_spill_rows"
IVF_SIDECAR_WRITES = "ivf_sidecar_writes"
IVF_SIDECAR_LOADS = "ivf_sidecar_loads"
IVF_SIDECAR_STALE = "ivf_sidecar_stale"
IVF_SIDECAR_ERRORS = "ivf_sidecar_errors"

# ---- tracing / flight recorder / exposition (utils.tracing, runtime.expo) --
TRACE_DUMPS = "trace_dumps"
TRACE_DUMP_ERRORS = "trace_dump_errors"
#: flight dumps deliberately not written while durability is degraded
#: (shed, exact accounting — the recorder must never contend with the
#: WAL for a dying disk's last bytes).
TRACE_DUMPS_SHED = "trace_dumps_shed"
#: span-JSONL sink write failures / degraded-mode sheds — per-sink
#: accounting, distinct from the dead-letter journal's ``journal_*``.
TRACE_SPAN_ERRORS = "trace_span_errors"
TRACE_SPANS_SHED = "trace_spans_shed"
EXPO_REQUESTS = "expo_requests"
EXPO_ERRORS = "expo_errors"
#: derived stage-attribution gauge family:
#: ``stage_share_b<bucket>_<detect|crop|embed|match>``
STAGE_SHARE_PREFIX = "stage_share_"
DEVICE_BUSY_FRACTION = "device_busy_fraction"

# ---- signals layer: SLO / health / watchdogs (runtime.slo) -----------------
#: health state machine gauge: 0 = ok, 1 = warn, 2 = critical.
HEALTH_STATE = "health_state"
SLO_EVALUATIONS = "slo_evaluations"
SLO_TRANSITIONS = "slo_transitions"
#: a gauge objective's ``value_fn`` raised — the probe is dead, its burn
#: reads 0 (no data is not a breach), but the failure is never silent.
SLO_PROBE_FAILURES = "slo_probe_failures"
#: a backstop ticker's ``SLOMonitor.tick()`` raised — the EVALUATION
#: failed, distinct from a dead gauge probe (``slo_probe_failures``):
#: alerting on this chases the monitor, not an objective's value_fn.
SLO_TICK_ERRORS = "slo_tick_errors"
#: per-objective burn-rate gauge family: ``slo_burn_<objective>`` (the
#: max of the short- and long-window burn rates at last evaluation).
SLO_BURN_PREFIX = "slo_burn_"
#: warn-level watchdog event counter family: ``slo_events_<reason>``
#: (e.g. ``slo_events_recompile_post_warmup``).
SLO_EVENTS_PREFIX = "slo_events_"
#: jit-cache misses observed on serving dispatches AFTER warmup compiled
#: the whole bucket ladder — each one is a mid-serving XLA compile the
#: prewarm design exists to prevent (the recompile watchdog's counter).
RECOMPILES_POST_WARMUP = "recompiles_post_warmup"

# ---- replication: writer lease / WAL-tailing read replicas -----------------
REPLICATION_LEASE_ACQUIRED = "replication_lease_acquired"
REPLICATION_LEASE_CONFLICTS = "replication_lease_conflicts"
REPLICATION_POLLS = "replication_polls"
REPLICATION_POLL_ERRORS = "replication_poll_errors"
REPLICATION_RECORDS_APPLIED = "replication_records_applied"
REPLICATION_ROWS_APPLIED = "replication_rows_applied"
REPLICATION_CORRUPT_RECORDS = "replication_corrupt_records"
REPLICATION_WAL_REOPENS = "replication_wal_reopens"
REPLICATION_RESYNCS = "replication_resyncs"
REPLICATION_ABORTS_AFTER_APPLY = "replication_aborts_after_apply"
REPLICATION_ENROLL_REJECTED = "replication_enroll_rejected"
#: replica staleness gauges: WAL rows visible but not yet applied, and the
#: age (seconds) of the oldest row at the moment the replica applied it.
REPLICATION_LAG_ROWS = "replication_lag_rows"
REPLICATION_LAG_S = "replication_lag_s"

# ---- embedder rollout (runtime.rollout + the version-fenced state) ---------
#: rollout phase gauge: 0 idle, 1 staging, 2 parity, 3 ready, 4 cutover,
#: 5 done (``runtime.rollout.PHASE_CODES``).
ROLLOUT_PHASE = "rollout_phase"
#: contiguous re-embedded rows durable in the stage file (the resume
#: watermark) vs the gallery rows the rollout must cover.
ROLLOUT_STAGED_ROWS = "rollout_staged_rows"
ROLLOUT_TOTAL_ROWS = "rollout_total_rows"
#: dual-score parity window: sliding top-1 agreement of old vs new
#: embedder on live traffic, and the sample count behind it.
ROLLOUT_PARITY_AGREEMENT = "rollout_parity_agreement"
ROLLOUT_PARITY_SAMPLES = "rollout_parity_samples"
ROLLOUT_STAGE_CHUNKS = "rollout_stage_chunks"
ROLLOUT_STAGE_RESUMES = "rollout_stage_resumes"
ROLLOUT_STAGE_ERRORS = "rollout_stage_errors"
ROLLOUT_CUTOVERS = "rollout_cutovers"
#: recovery found a fsynced cutover fence with no post-cutover checkpoint
#: and completed the swap from the staged shard set.
ROLLOUT_CUTOVERS_COMPLETED_RECOVERY = "rollout_cutovers_completed_recovery"
ROLLOUT_CUTOVER_BLOCKED = "rollout_cutover_blocked"
ROLLOUT_ROLLBACKS = "rollout_rollbacks"
#: the serving embedder version gauge (stamped into checkpoints, WAL rows
#: and published results; one served shard set holds exactly one).
ROLLOUT_EMBEDDER_VERSION = "rollout_embedder_version"
#: version-fence rejections: an enrollment whose embeddings carry another
#: version than the serving gallery (failed closed, no seq burned).
ROLLOUT_VERSION_MISMATCHES = "rollout_version_mismatches"
#: rows a replay/tail consumer REFUSED to apply across the version fence
#: (can only arise from damaged state — loud, never mixed in).
ROLLOUT_VERSION_SKIPPED_ROWS = "rollout_version_skipped_rows"
#: a read replica parked on a cutover fence, waiting for the new-version
#: checkpoint to re-anchor on (gauge 1/0), and the re-anchors completed.
ROLLOUT_REPLICA_AWAITING = "rollout_replica_awaiting"
ROLLOUT_REPLICA_REANCHORS = "rollout_replica_reanchors"
#: parity/live-traffic observation hook failures (publish path; counted,
#: never propagated into the serving loop).
ROLLOUT_OBSERVE_ERRORS = "rollout_observe_errors"
#: cutover WAL fence records appended.
WAL_CUTOVER_RECORDS = "wal_cutover_records"

# ---- versioned model registry (runtime.registry, ISSUE 18) -----------------
#: per-role served-version gauge family ``model_version_<role>`` (the
#: /prom mirror of the durable manifest: embedder, detector, cascade).
MODEL_VERSION_PREFIX = "model_version_"
#: registry swap phase gauge: 0 idle, 1 parity, 2 ready, 3 cutover,
#: 4 watch, 5 done, 6 rolled_back (``runtime.registry.PHASE_CODES``).
REGISTRY_PHASE = "registry_phase"
#: detection-parity window (old vs candidate detector, box-overlap
#: verdict match on live sampled frames) and the sample count behind it.
REGISTRY_PARITY_AGREEMENT = "registry_parity_agreement"
REGISTRY_PARITY_SAMPLES = "registry_parity_samples"
#: fenced registry swaps performed, and swaps the parity gate refused.
REGISTRY_SWAPS = "registry_swaps"
REGISTRY_SWAPS_BLOCKED = "registry_swaps_blocked"
#: recovery found a fsynced registry fence whose manifest install never
#: ran and COMPLETED it (staged params verified) / cleanly ABANDONED it
#: (params missing or damaged — the version number is retired).
REGISTRY_SWAPS_COMPLETED_RECOVERY = "registry_swaps_completed_recovery"
REGISTRY_SWAPS_ABANDONED_RECOVERY = "registry_swaps_abandoned_recovery"
#: post-cutover watch regressions rolled back automatically (each one
#: forces a ``registry_auto_rollback`` flight dump).
REGISTRY_AUTO_ROLLBACKS = "registry_auto_rollbacks"
#: FaceGate retrains riding a detector swap (``evaluate_gate`` scores
#: stage 1 against detector verdicts, so the pair cuts over together).
REGISTRY_GATE_RETRAINS = "registry_gate_retrains"
#: eager tracker/cascade cache flushes on a role's cutover.
REGISTRY_CACHE_FLUSHES = "registry_cache_flushes"
#: live-observation hook failures on the publish path (counted, never
#: propagated into the serving loop — like rollout_observe_errors).
REGISTRY_OBSERVE_ERRORS = "registry_observe_errors"
#: registry_cutover WAL fence records appended, and abandon tombstones.
WAL_REGISTRY_RECORDS = "wal_registry_records"
WAL_REGISTRY_ABORTS = "wal_registry_aborts"

# ---- topic router (runtime.replication.TopicRouter) ------------------------
ROUTER_ROUTED = "router_routed"
#: per-reason rejection family: ``router_rejected_<reason>``
ROUTER_REJECTED_PREFIX = "router_rejected_"
ROUTER_BUDGET_SPILLS = "router_budget_spills"
ROUTER_FAILOVERS = "router_failovers"
ROUTER_RECOVERIES = "router_recoveries"
#: a replica cordoned (excluded from rendezvous) for a planned drain —
#: the cutover re-anchor path; distinct from health failover.
ROUTER_CUTOVER_DRAINS = "router_cutover_drains"
ROUTER_HEALTH_PROBE_FAILURES = "router_health_probe_failures"
#: consecutive-probe-exception accounting (ISSUE 16): every probe raise
#: increments this, but the per-replica streak is capped and the warn log
#: fires once per into-erroring transition — a permanently-raising probe
#: is one log line, not one per cycle.
ROUTER_PROBE_ERRORS = "router_probe_errors"
ROUTER_REPLICAS = "router_replicas"
ROUTER_HEALTHY_REPLICAS = "router_healthy_replicas"
#: interactive-priority hedged dispatch (ISSUE 16): re-sends of an
#: interactive frame to the next rendezvous-preferred replica after the
#: hedge deadline; ``wins`` = the hedged copy's result arrived first,
#: ``wasted`` = the original won and the hedge's result was deduped.
ROUTER_HEDGES = "router_hedges"
ROUTER_HEDGE_WINS = "router_hedge_wins"
ROUTER_HEDGE_WASTED = "router_hedge_wasted"

# ---- supervisor ------------------------------------------------------------
SUPERVISOR_CHECKPOINTS = "supervisor_checkpoints"
SUPERVISOR_RESTARTS = "supervisor_restarts"
SUPERVISOR_STALLS = "supervisor_stalls"
SUPERVISOR_GAVE_UP = "supervisor_gave_up"
SUPERVISOR_DURABLE_RESTORES = "supervisor_durable_restores"


# ---- ledger source-of-truth tables (ocvf-lint ledger-registry-coherence) ---
# The admission-ledger invariant is
#   admitted == Σ(LEDGER_COMPLETION_COUNTERS) + Σ(LEDGER_DROP_COUNTERS)
# at quiescence.  These two tables are THE definition of "terminal status":
# the runtime (RecognizerService.ledger/frames_in_system), the span reducer
# (tracing.account_spans), the chaos soak's span mirror, and the settle-once
# lint rule all derive from them.  A new terminal bucket starts here; the
# ledger-registry-coherence rule flags every mirror site that missed it.
LEDGER_COMPLETION_COUNTERS = (
    FRAMES_COMPLETED,
    FRAMES_COMPLETED_EMPTY,
    FRAMES_COMPLETED_CACHED,
)
LEDGER_DROP_COUNTERS = (
    FRAMES_MALFORMED,
    FRAMES_DROPPED_DECODE,
    BATCHER_DROPPED_MALFORMED,
    BATCHER_DROPPED_OVERFLOW,
    BATCHER_DROPPED_STALE,
    BATCHER_DROPPED_CLOSED,
    FRAMES_DROPPED_BROWNOUT,
    FRAMES_DEAD_LETTERED,
    FRAMES_FAILED,
    FRAMES_DROPPED_CRASHED,
)

#: The dynamic prefix families promtext folds into labeled Prometheus
#: families (plus STAGE_SHARE_PREFIX, which gets its own two-label
#: parser).  promtext._LABEL_FAMILIES must mirror this set exactly.
PROM_FOLDED_PREFIXES = (
    FRAMES_REJECTED_PREFIX,
    BATCHER_DROPPED_PREFIX,
    SLO_EVENTS_PREFIX,
    SLO_BURN_PREFIX,
    TRACK_FLUSHES_PREFIX,
    TRANSPORT_FAULTS_PREFIX,
    ROUTER_REJECTED_PREFIX,
)


def all_names():
    """Every registered full metric name (prefix families excluded) —
    used by tests to assert the registry has no duplicate values."""
    return sorted(v for k, v in globals().items()
                  if k.isupper() and not k.endswith("_PREFIX")
                  and isinstance(v, str))


def all_prefixes():
    return sorted(v for k, v in globals().items()
                  if k.endswith("_PREFIX") and isinstance(v, str))
