"""Structured per-batch metrics (SURVEY.md §5.5): counters + latency
percentiles + a JSONL sink. The north-star metric (faces/sec/chip) falls out
of the per-batch records."""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict, deque
from typing import Dict, IO, Optional


class Metrics:
    """Thread-safe counters + gauges + bounded latency windows + optional
    JSONL sink."""

    def __init__(self, sink: Optional[IO[str]] = None, window: int = 512):
        self._lock = threading.Lock()
        # The sink gets its OWN lock: a slow JSONL sink (disk stall, full
        # pipe) must serialize log lines against each other, but it must
        # never stall every counter incr on the serving hot path behind a
        # write(2) — found by ocvf-lint blocking-under-lock.
        self._sink_lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._latencies: Dict[str, deque] = defaultdict(lambda: deque(maxlen=window))
        self._sink = sink

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self._latencies[name].append(seconds)

    def set_gauge(self, name: str, value: float) -> None:
        """Last-write-wins instantaneous value (e.g. the batcher's current
        adaptive flush deadline) — reported as-is in ``summary``."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default: float = float("nan")) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def counters(self) -> Dict[str, float]:
        """One atomic snapshot of every counter — the chaos tests compare
        this against a FaultInjector's injected-fault ledger, so the read
        must not interleave with concurrent incrs."""
        with self._lock:
            return dict(self._counters)

    def counters_with_prefix(self, prefix: str) -> Dict[str, float]:
        """Atomic snapshot of the counters under one namespace — e.g.
        ``frames_rejected_``, the admission layer's per-reason rejects,
        which the overload soak/bench report grouped this way."""
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def sum_counters(self, positive, negative=()) -> float:
        """Atomic ``sum(positive) - sum(negative)`` over counter names —
        one lock acquisition, no dict copy. The admission bound reads its
        in-system count through this on every offered frame, so it must
        stay allocation-free under flood load."""
        with self._lock:
            c = self._counters
            return (sum(c.get(n, 0.0) for n in positive)
                    - sum(c.get(n, 0.0) for n in negative))

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            values = sorted(self._latencies.get(name, ()))
        if not values:
            return float("nan")
        idx = min(int(q / 100.0 * len(values)), len(values) - 1)
        return values[idx]

    def reset_window(self, name: Optional[str] = None) -> None:
        """Clear one latency window (or all of them) without touching
        counters/gauges — bench reuse between a warm phase and a measured
        phase. A cleared window reports explicit ``None`` percentiles in
        ``summary`` until it sees new observations (never stale or zero
        values masquerading as measurements)."""
        with self._lock:
            if name is not None:
                window = self._latencies.get(name)
                if window is not None:
                    window.clear()
            else:
                for window in self._latencies.values():
                    window.clear()

    def log(self, event: str, **fields) -> None:
        if self._sink is None:
            return
        record = {"ts": time.time(), "event": event, **fields}
        line = json.dumps(record)
        # I/O deliberately held under the sink lock: serializing writers is
        # this lock's entire purpose and nothing on the counter path ever
        # takes it.
        with self._sink_lock:  # ocvf-lint: boundary-block=blocking-under-lock -- sink lock exists solely to serialize sink writes; counter paths never take it
            self._sink.write(line + "\n")
            self._sink.flush()

    def summary(self) -> Dict[str, Optional[float]]:
        """Counters + gauges + per-window percentiles. A window that is
        known but currently EMPTY (after ``reset_window``) reports
        explicit ``None`` values — never a misleading zero, never a raise
        — so a consumer can tell "no data yet" from "measured 0 ms"."""
        with self._lock:
            out: Dict[str, Optional[float]] = dict(self._counters)
            out.update(self._gauges)
            for name, values in self._latencies.items():
                if values:
                    ordered = sorted(values)
                    out[f"{name}_p50_ms"] = ordered[len(ordered) // 2] * 1e3
                    out[f"{name}_p95_ms"] = ordered[min(int(0.95 * len(ordered)), len(ordered) - 1)] * 1e3
                else:
                    out[f"{name}_p50_ms"] = None
                    out[f"{name}_p95_ms"] = None
        return out
