"""Structured per-batch metrics (SURVEY.md §5.5): counters + latency
percentiles + a JSONL sink. The north-star metric (faces/sec/chip) falls out
of the per-batch records.

Latency windows are **rolling log-bucket histograms**
(``utils.histogram.RollingHistogram``) as of the signals layer: an
``observe`` is one O(1) bucket increment, a percentile read is a
~100-bucket walk (exact to one bucket width — see the histogram module's
contract), the horizon is true wall-clock time (``window_s`` seconds,
sliced), and memory per window is flat forever — the old sample deques
were bounded only between ``reset_window()`` calls and reported "the last
N samples" over whatever time span that happened to be. The observe /
``percentile`` / ``summary`` surface is unchanged, including the explicit
``None`` percentiles for known-but-empty windows; ``summary`` additionally
reports ``_p99_ms`` now that p99 is cheap (the SLO layer's headline
quantile). The SLO monitor reads the same windows through
``fraction_above``/``window_count``, and ``/prom`` renders them through
``export_state``."""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Any, Dict, IO, Optional, Tuple

from opencv_facerecognizer_tpu.utils.histogram import RollingHistogram


class Metrics:
    """Thread-safe counters + gauges + rolling-histogram latency windows +
    optional JSONL sink.

    ``window_s``/``window_slices`` size every latency window's rolling
    ring: the default 600 s over 20 slices covers the SLO layer's stock
    long window at 30 s horizon granularity (a requested horizon is
    rounded UP to whole slices — see ``RollingHistogram.merged``). Tests
    and soaks that need fast expiry pass finer slicing."""

    def __init__(self, sink: Optional[IO[str]] = None,
                 window_s: float = 600.0, window_slices: int = 20):
        self._lock = threading.Lock()
        # The sink gets its OWN lock: a slow JSONL sink (disk stall, full
        # pipe) must serialize log lines against each other, but it must
        # never stall every counter incr on the serving hot path behind a
        # write(2) — found by ocvf-lint blocking-under-lock.
        self._sink_lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._window_s = float(window_s)
        self._window_slices = int(window_slices)
        self._latencies: Dict[str, RollingHistogram] = defaultdict(
            lambda: RollingHistogram(self._window_s, self._window_slices))
        self._sink = sink

    @property
    def window_s(self) -> float:
        """Rolling-horizon of every latency window (seconds). Reads over a
        longer horizon silently see at most this much data — consumers
        with configurable horizons (the SLO monitor) validate against it
        at construction."""
        return self._window_s

    @property
    def window_slice_s(self) -> float:
        """Ring resolution (seconds per slice): a horizon below this reads
        a full slice's worth of data anyway. The SLO monitor refuses
        sub-slice windows against it at construction."""
        return self._window_s / self._window_slices

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self._latencies[name].observe(seconds)  # ocvf-lint: disable=metrics-registry -- RollingHistogram.observe takes the sample VALUE; the metric name was validated at this method's own call site

    def set_gauge(self, name: str, value: float) -> None:
        """Last-write-wins instantaneous value (e.g. the batcher's current
        adaptive flush deadline) — reported as-is in ``summary``."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default: float = float("nan")) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def counters(self) -> Dict[str, float]:
        """One atomic snapshot of every counter — the chaos tests compare
        this against a FaultInjector's injected-fault ledger, so the read
        must not interleave with concurrent incrs."""
        with self._lock:
            return dict(self._counters)

    def counters_with_prefix(self, prefix: str) -> Dict[str, float]:
        """Atomic snapshot of the counters under one namespace — e.g.
        ``frames_rejected_``, the admission layer's per-reason rejects,
        which the overload soak/bench report grouped this way."""
        with self._lock:
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def sum_counters(self, positive, negative=()) -> float:
        """Atomic ``sum(positive) - sum(negative)`` over counter names —
        one lock acquisition, no dict copy. The admission bound reads its
        in-system count through this on every offered frame, so it must
        stay allocation-free under flood load."""
        with self._lock:
            c = self._counters
            return (sum(c.get(n, 0.0) for n in positive)
                    - sum(c.get(n, 0.0) for n in negative))

    def percentile(self, name: str, q: float,
                   horizon_s: Optional[float] = None) -> float:
        """The window's ``q``-percentile in seconds over the trailing
        ``horizon_s`` (default: the full rolling window); NaN when the
        window is unknown or empty. Exact to one histogram bucket."""
        with self._lock:
            window = self._latencies.get(name)
            if window is None:
                return float("nan")
            return window.quantile(q, horizon_s=horizon_s)

    def fraction_above(self, name: str, threshold_s: float,
                       horizon_s: Optional[float] = None) -> float:
        """Fraction of the window's observations above ``threshold_s``
        over the trailing horizon — the SLO burn-rate monitor's error-rate
        read for latency objectives. 0.0 for unknown/empty windows (no
        data never reads as a breach; ``window_count`` tells them apart)."""
        with self._lock:
            window = self._latencies.get(name)
            if window is None:
                return 0.0
            return window.fraction_above(threshold_s, horizon_s=horizon_s)

    def window_count(self, name: str,
                     horizon_s: Optional[float] = None) -> int:
        """Observations currently inside the trailing horizon."""
        with self._lock:
            window = self._latencies.get(name)
            return 0 if window is None else window.count(horizon_s=horizon_s)

    def reset_window(self, name: Optional[str] = None) -> None:
        """Clear one latency window (or all of them) without touching
        counters/gauges — bench reuse between a warm phase and a measured
        phase. A cleared window reports explicit ``None`` percentiles in
        ``summary`` until it sees new observations (never stale or zero
        values masquerading as measurements)."""
        with self._lock:
            if name is not None:
                window = self._latencies.get(name)
                if window is not None:
                    window.clear()
            else:
                for window in self._latencies.values():
                    window.clear()

    def log(self, event: str, **fields) -> None:
        if self._sink is None:
            return
        record = {"ts": time.time(), "event": event, **fields}
        line = json.dumps(record)
        # I/O deliberately held under the sink lock: serializing writers is
        # this lock's entire purpose and nothing on the counter path ever
        # takes it.
        with self._sink_lock:  # ocvf-lint: boundary-block=blocking-under-lock -- sink lock exists solely to serialize sink writes; counter paths never take it
            self._sink.write(line + "\n")
            self._sink.flush()

    def summary(self) -> Dict[str, Optional[float]]:
        """Counters + gauges + per-window percentiles (p50/p95/p99, ms,
        bucket precision). A window that is known but currently EMPTY
        (after ``reset_window`` or full rolling expiry) reports explicit
        ``None`` values — never a misleading zero, never a raise — so a
        consumer can tell "no data yet" from "measured 0 ms"."""
        with self._lock:
            out: Dict[str, Optional[float]] = dict(self._counters)
            out.update(self._gauges)
            for name, window in self._latencies.items():
                merged = window.merged()
                if merged.count:
                    out[f"{name}_p50_ms"] = merged.quantile(50) * 1e3
                    out[f"{name}_p95_ms"] = merged.quantile(95) * 1e3
                    out[f"{name}_p99_ms"] = merged.quantile(99) * 1e3
                else:
                    out[f"{name}_p50_ms"] = None
                    out[f"{name}_p95_ms"] = None
                    out[f"{name}_p99_ms"] = None
        return out

    def export_state(self) -> Tuple[Dict[str, float], Dict[str, float],
                                    Dict[str, Dict[str, Any]]]:
        """One atomic ``(counters, gauges, histograms)`` snapshot for the
        Prometheus exposition (``runtime.promtext``): histograms are the
        full-window merge in ``LogBucketHistogram.snapshot`` shape
        (bounds / per-bucket counts / count / sum). Empty-but-known
        windows export with ``count == 0`` — a scraper sees the family
        exists even before traffic."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {name: window.merged().snapshot()
                     for name, window in self._latencies.items()}
        return counters, gauges, hists
