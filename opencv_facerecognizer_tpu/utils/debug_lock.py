"""Instrumented locks that record acquisition order — the *dynamic*
backstop to ocvf-lint's static ``lock-order`` rule.

The static checker sees lexical nesting and hint-resolved calls; it cannot
see orders that only materialize at runtime (callbacks, hooks, locks passed
across objects).  A ``LockOrderMonitor`` wraps the stack's real locks in
``DebugLock``s, maintains each thread's held-lock stack, and records every
(held, acquired) edge.  ``check()`` raises if any two locks were ever taken
in both orders — the AB/BA deadlock shape — and ``edges()`` feeds the
chaos tests' cross-check against the statically derived graph.

Zero overhead when not used: production code never imports this; tests
swap instances' lock attributes before starting threads.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderError(AssertionError):
    """Two locks were observed acquired in both orders (or one was
    re-entered while held) — a latent deadlock."""


class LockOrderMonitor:
    """Shared recorder for a family of DebugLocks.

    ``raise_on_inversion=True`` raises at the acquiring site the moment an
    edge's reverse is already on record — maximal debuggability, but it
    throws inside whatever thread trips it.  The default records silently
    and lets the test call ``check()`` at the end, so supervised serving
    threads (which catch Exception by design) can't eat the signal."""

    def __init__(self, raise_on_inversion: bool = False):
        self._raise = raise_on_inversion
        self._mu = threading.Lock()
        #: (held, acquired) -> observation count
        self._edges: Dict[Tuple[str, str], int] = {}
        self._local = threading.local()

    # ---- held-stack bookkeeping (called by DebugLock) ----

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def _before_acquire(self, name: str) -> None:
        stack = self._stack()
        if name in stack:
            raise LockOrderError(
                f"DebugLock {name!r} re-entered while already held "
                f"(held stack: {stack}) — this deadlocks a plain Lock")
        if stack:
            edge = (stack[-1], name)
            with self._mu:
                self._edges[edge] = self._edges.get(edge, 0) + 1
                inverted = self._raise and (name, stack[-1]) in self._edges
            if inverted:
                raise LockOrderError(
                    f"lock-order inversion: acquired {name!r} while holding "
                    f"{stack[-1]!r}, but the reverse order is also on record")

    def _after_acquire(self, name: str) -> None:
        self._stack().append(name)

    def _after_release(self, name: str) -> None:
        stack = self._stack()
        # remove the most recent occurrence — releases may be out of LIFO
        # order (Condition.wait releases mid-stack)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # ---- public API ----

    def debug_lock(self, name: str,
                   inner: Optional[threading.Lock] = None) -> "DebugLock":
        return DebugLock(self, name, inner=inner)

    def edges(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self._edges)

    def inversions(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted((a, b) for (a, b) in self._edges
                          if a < b and (b, a) in self._edges)

    def check(self) -> None:
        """Raise LockOrderError if any inversion was recorded."""
        bad = self.inversions()
        if bad:
            raise LockOrderError(
                f"lock-order inversions observed at runtime: {bad}")


class DebugLock:
    """Drop-in ``threading.Lock`` replacement reporting to a monitor.

    Also works as the lock behind a ``threading.Condition`` — it exposes
    ``_is_owned`` so the Condition's ownership asserts use the real owner
    thread instead of the acquire(0) probe, and releases are tracked even
    when ``wait()`` drops the lock mid-stack."""

    def __init__(self, monitor: LockOrderMonitor, name: str,
                 inner: Optional[threading.Lock] = None):
        self._monitor = monitor
        self.name = name
        self._inner = inner if inner is not None else threading.Lock()
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._monitor._before_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._monitor._after_acquire(self.name)
        return got

    def release(self) -> None:
        self._owner = None
        self._monitor._after_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self.name!r} inner={self._inner!r}>"
