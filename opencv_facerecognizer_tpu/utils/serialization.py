"""Pickle-free model checkpointing (SURVEY.md §5.4).

The reference checkpointed by pickling the whole ``PredictableModel``
(``facerec/serialization.py`` save_model/load_model — SURVEY.md §2.1). That
is unsafe (arbitrary code execution on load) and version-brittle. Rebuild:

- a *spec* — a JSON-safe nested dict ``{"type": registry-name, "config":
  {...}}`` describing how to reconstruct every plugin, and
- a *state* — a nested dict of arrays (the fit results / enrolled gallery),
  serialized with flax's msgpack (no code, just tensors + structure).

``save_model`` writes one msgpack file with header/spec/state;
``load_model`` rebuilds the plugin tree from the registry and restores
arrays. Anything implementing get_config/from_config/get_state/set_state
participates — including operators, which recursively serialize children.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np
from flax import serialization as flax_serialization

FORMAT_VERSION = 1

#: registry-name -> class, populated lazily to avoid import cycles.
_REGISTRY: Dict[str, type] = {}


def _registry() -> Dict[str, type]:
    if not _REGISTRY:
        from opencv_facerecognizer_tpu.models import classifier as c
        from opencv_facerecognizer_tpu.models import feature as f
        from opencv_facerecognizer_tpu.models import model as m
        from opencv_facerecognizer_tpu.models import operators as o

        for cls in (
            f.Identity,
            f.PCA,
            f.LDA,
            f.Fisherfaces,
            f.SpatialHistogram,
            f.TanTriggsPreprocessing,
            f.HistogramEqualization,
            f.Resize,
            f.MinMaxNormalize,
            o.ChainOperator,
            o.CombineOperator,
            o.CombineOperatorND,
            c.NearestNeighbor,
            c.SVM,
            c.KernelSVM,
            m.PredictableModel,
            m.ExtendedPredictableModel,
        ):
            _REGISTRY[cls.name] = cls
        # CNNEmbedding lives in its own module (heavier deps); it is part
        # of the default registry all the same — a checkpoint saved through
        # the plain save_model API must load without first touching the
        # trainer or the serving app (round-3 drive finding).
        from opencv_facerecognizer_tpu.models import embedder as e

        _REGISTRY[e.CNNEmbedding.name] = e.CNNEmbedding
    return _REGISTRY


def register(cls: type) -> type:
    """Register an external plugin class (usable as a decorator)."""
    _registry()[cls.name] = cls
    return cls


def serialize_spec(obj: Any) -> dict:
    """Object -> JSON-safe reconstruction spec {"type", "config"}."""
    return {"type": obj.name, "config": obj.get_config()}


def deserialize_spec(spec: dict) -> Any:
    reg = _registry()
    if spec["type"] not in reg:
        raise KeyError(
            f"unknown plugin type {spec['type']!r}; registered: {sorted(reg)}"
        )
    return reg[spec["type"]].from_config(spec["config"])


def _to_numpy_tree(state: Any) -> Any:
    if isinstance(state, dict):
        return {k: _to_numpy_tree(v) for k, v in state.items()}
    return np.asarray(state)


def save_model(filename: str, model: Any) -> None:
    """Write {header, spec, state} as one msgpack blob. No pickle anywhere."""
    payload = {
        "header": {"format_version": FORMAT_VERSION, "spec_json": json.dumps(serialize_spec(model))},
        "state": _to_numpy_tree(model.get_state()),
    }
    blob = flax_serialization.msgpack_serialize(payload)
    with open(filename, "wb") as fh:
        fh.write(blob)


def load_model(filename: str) -> Any:
    with open(filename, "rb") as fh:
        payload = flax_serialization.msgpack_restore(fh.read())
    header = payload["header"]
    version = int(header["format_version"])
    if version > FORMAT_VERSION:
        raise ValueError(f"checkpoint format v{version} is newer than supported v{FORMAT_VERSION}")
    model = deserialize_spec(json.loads(header["spec_json"]))
    model.set_state(payload.get("state", {}))
    return model
