"""Pickle-free model checkpointing (SURVEY.md §5.4).

The reference checkpointed by pickling the whole ``PredictableModel``
(``facerec/serialization.py`` save_model/load_model — SURVEY.md §2.1). That
is unsafe (arbitrary code execution on load) and version-brittle. Rebuild:

- a *spec* — a JSON-safe nested dict ``{"type": registry-name, "config":
  {...}}`` describing how to reconstruct every plugin, and
- a *state* — a nested dict of arrays (the fit results / enrolled gallery),
  serialized with flax's msgpack (no code, just tensors + structure).

``save_model`` writes one msgpack file with header/spec/state;
``load_model`` rebuilds the plugin tree from the registry and restores
arrays. Anything implementing get_config/from_config/get_state/set_state
participates — including operators, which recursively serialize children.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import numpy as np
from flax import serialization as flax_serialization

FORMAT_VERSION = 1


class CheckpointCorruptError(ValueError):
    """A checkpoint file failed decode/validation — truncated, garbage, or
    missing its header. Deliberately a ``ValueError`` subclass so existing
    broad handlers keep working, but precise enough that recovery code can
    fall back to an older checkpoint instead of treating the failure as a
    code bug."""


def fsync_directory(path: str) -> None:
    """fsync a directory so a just-renamed entry survives a power cut.
    Best-effort: some filesystems refuse O_RDONLY dir fsync — a failure
    only widens the durability window back to the kernel's writeback."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(filename: str, blob: bytes,
                       keep_previous: int = 0) -> None:
    """Crash-safe file write: tmp in the same directory + flush + fsync +
    atomic rename + directory fsync. A crash at any point leaves either the
    old file intact or the new one complete — never a torn half-write under
    the final name (the seed's bare ``open+write`` could corrupt the ONLY
    checkpoint mid-save). With ``keep_previous > 0`` the existing file's
    content is preserved at ``filename.1..N`` — hardlinked AFTER the tmp
    is durable, so neither a write failure (ENOSPC) nor process death
    between the rotate and the install ever leaves ``filename`` absent or
    stale-only-under-``.1``."""
    filename = str(filename)
    directory = os.path.dirname(os.path.abspath(filename))
    # pid-unique tmp: two concurrent writers of the same target must not
    # share a staging file, or one's os.replace could install the other's
    # half-written bytes — the exact torn-file class this helper prevents
    tmp = f"{filename}.tmp.{os.getpid()}"
    fh = open(tmp, "wb")
    try:
        fh.write(blob)
        fh.flush()
        os.fsync(fh.fileno())
    finally:
        fh.close()
    if keep_previous > 0:
        rotate_backups(filename, keep_previous)
    os.replace(tmp, filename)
    fsync_directory(directory)


def atomic_write_text(filename: str, text: str,
                      keep_previous: int = 0) -> None:
    """``atomic_write_bytes`` for text — the required way to write reports,
    JSON artifacts and any other file whose torn half-write would be read
    later (ocvf-lint rule ``non-atomic-write`` flags bare ``open(.., 'w')``)."""
    atomic_write_bytes(filename, text.encode("utf-8"),
                       keep_previous=keep_previous)


def atomic_write_json(filename: str, obj: Any, *, indent: int = 2,
                      sort_keys: bool = False, keep_previous: int = 0) -> None:
    """Crash-safe ``json.dump`` replacement: serialize fully in memory, then
    one atomic tmp+fsync+rename install.  ``json.dump(obj, fh)`` writes
    incrementally, so a crash mid-dump leaves a truncated-but-parseable-
    prefix trap; this never does."""
    text = json.dumps(obj, indent=indent, sort_keys=sort_keys)
    atomic_write_text(filename, text + "\n", keep_previous=keep_previous)


def rotate_backups(filename: str, keep: int) -> None:
    """Preserve the current file's content at ``filename.1`` (shifting
    ``.1 -> .2 -> ... -> .keep``, dropping the oldest) so an atomic
    overwrite can retain previous versions — ``ocvf-train
    --keep-checkpoints`` uses this to keep the last N model checkpoints
    across retrains.

    ``filename`` itself is HARDLINKED to ``.1``, not renamed: the final
    name stays present throughout, so a SIGKILL/power cut anywhere in the
    rotate-then-install sequence never leaves the path empty (a rename
    here would open exactly that window). On a filesystem without
    hardlinks the rename fallback reopens that (tiny) window — renames
    only, never data loss."""
    if keep <= 0 or not os.path.exists(filename):
        return
    oldest = f"{filename}.{keep}"
    if os.path.exists(oldest):
        os.remove(oldest)
    for i in range(keep - 1, 0, -1):
        src = f"{filename}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{filename}.{i + 1}")
    try:
        os.link(filename, f"{filename}.1")
    except OSError:
        os.replace(filename, f"{filename}.1")

#: registry-name -> class, populated lazily to avoid import cycles.
_REGISTRY: Dict[str, type] = {}


def _registry() -> Dict[str, type]:
    if not _REGISTRY:
        from opencv_facerecognizer_tpu.models import classifier as c
        from opencv_facerecognizer_tpu.models import feature as f
        from opencv_facerecognizer_tpu.models import model as m
        from opencv_facerecognizer_tpu.models import operators as o

        for cls in (
            f.Identity,
            f.PCA,
            f.LDA,
            f.Fisherfaces,
            f.SpatialHistogram,
            f.TanTriggsPreprocessing,
            f.HistogramEqualization,
            f.Resize,
            f.MinMaxNormalize,
            o.ChainOperator,
            o.CombineOperator,
            o.CombineOperatorND,
            c.NearestNeighbor,
            c.SVM,
            c.KernelSVM,
            m.PredictableModel,
            m.ExtendedPredictableModel,
        ):
            _REGISTRY[cls.name] = cls
        # CNNEmbedding lives in its own module (heavier deps); it is part
        # of the default registry all the same — a checkpoint saved through
        # the plain save_model API must load without first touching the
        # trainer or the serving app (round-3 drive finding).
        from opencv_facerecognizer_tpu.models import embedder as e

        _REGISTRY[e.CNNEmbedding.name] = e.CNNEmbedding
    return _REGISTRY


def register(cls: type) -> type:
    """Register an external plugin class (usable as a decorator)."""
    _registry()[cls.name] = cls
    return cls


def serialize_spec(obj: Any) -> dict:
    """Object -> JSON-safe reconstruction spec {"type", "config"}."""
    return {"type": obj.name, "config": obj.get_config()}


def deserialize_spec(spec: dict) -> Any:
    reg = _registry()
    if spec["type"] not in reg:
        raise KeyError(
            f"unknown plugin type {spec['type']!r}; registered: {sorted(reg)}"
        )
    return reg[spec["type"]].from_config(spec["config"])


def _to_numpy_tree(state: Any) -> Any:
    if isinstance(state, dict):
        return {k: _to_numpy_tree(v) for k, v in state.items()}
    return np.asarray(state)


def save_model(filename: str, model: Any, keep_previous: int = 0) -> None:
    """Write {header, spec, state} as one msgpack blob. No pickle anywhere.

    The write is atomic (tmp + fsync + rename): a crash mid-save leaves the
    previous checkpoint intact, never a truncated file under ``filename``.
    ``keep_previous > 0`` additionally rotates the existing file to
    ``filename.1`` (... ``.keep_previous``) before the rename."""
    payload = {
        "header": {"format_version": FORMAT_VERSION, "spec_json": json.dumps(serialize_spec(model))},
        "state": _to_numpy_tree(model.get_state()),
    }
    blob = flax_serialization.msgpack_serialize(payload)
    atomic_write_bytes(filename, blob, keep_previous=keep_previous)


def load_model(filename: str) -> Any:
    with open(filename, "rb") as fh:
        blob = fh.read()
    try:
        payload = flax_serialization.msgpack_restore(blob)
    except Exception as exc:  # noqa: BLE001 — msgpack raises assorted types
        raise CheckpointCorruptError(
            f"checkpoint {filename!r} failed msgpack decode (truncated or "
            f"garbage): {exc!r}") from exc
    if not isinstance(payload, dict) or "header" not in payload:
        raise CheckpointCorruptError(
            f"checkpoint {filename!r} decoded but has no header — not an "
            f"ocvf model checkpoint")
    header = payload["header"]
    try:
        version = int(header["format_version"])
        spec = json.loads(header["spec_json"])
    except (KeyError, TypeError, ValueError) as exc:
        raise CheckpointCorruptError(
            f"checkpoint {filename!r} has a malformed header: {exc!r}") from exc
    if version > FORMAT_VERSION:
        raise ValueError(f"checkpoint format v{version} is newer than supported v{FORMAT_VERSION}")
    model = deserialize_spec(spec)
    model.set_state(payload.get("state", {}))
    return model
