"""Dataset utilities (SURVEY.md §2.1 "Matrix/dataset utils").

``read_images`` walks the reference-family dataset layout (one folder per
subject containing face images) and returns (images [N, H, W] float32,
labels [N] int, subject_names). Decoding uses cv2 when present, else PIL —
both are host-side I/O; everything downstream is device arrays.

``make_synthetic_faces`` generates a deterministic ORL-like dataset (distinct
per-subject structure + per-sample noise/illumination) so the validation
harness and tests run without network access to the real AT&T/LFW data
(the environment has zero egress — SURVEY.md §0).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np


def _imread_gray(path: str) -> Optional[np.ndarray]:
    # Native C++ decode first (PGM/PPM/BMP — the classic face-dataset
    # formats; SURVEY.md §2.2's host decode path was native in the
    # reference too). Unsupported formats fall through to cv2/PIL.
    from opencv_facerecognizer_tpu.utils import native

    if native.handles(path):
        img = native.load_gray(path)
        if img is not None:
            return img
    try:
        import cv2

        img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
        return None if img is None else img.astype(np.float32)
    except ImportError:
        pass
    try:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("L"), dtype=np.float32)
    except Exception:  # ocvf-lint: disable=swallowed-exception -- None is this loader's documented contract: the dataset walker skips unreadable files, and a corrupt image in a training dir is data, not a fault
        return None


def _resize_gray(img: np.ndarray, image_size: Tuple[int, int]) -> np.ndarray:
    """Host-side bilinear resize to (H, W). cv2 when importable, else PIL,
    else the device resize — this environment ships no usable cv2, and the
    CLI entry points always pass image_size, so the fallback chain is the
    difference between the apps starting and an ImportError."""
    h, w = int(image_size[0]), int(image_size[1])
    if img.shape == (h, w):
        return np.asarray(img, dtype=np.float32)
    try:
        import cv2

        return cv2.resize(img, (w, h)).astype(np.float32)
    except ImportError:
        pass
    try:
        from PIL import Image

        resized = Image.fromarray(np.asarray(img, np.float32), mode="F").resize(
            (w, h), Image.BILINEAR
        )
        return np.asarray(resized, dtype=np.float32)
    except ImportError:
        from opencv_facerecognizer_tpu.ops import image as image_ops

        return np.asarray(image_ops.resize(img, (h, w)), dtype=np.float32)


def read_images(
    path: str, image_size: Optional[Tuple[int, int]] = None
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Walk ``path/<subject>/<image files>`` -> (images, labels, names).

    Subjects are sorted for determinism; unreadable files are skipped with a
    warning count rather than aborting enrolment (SURVEY.md §5.3 graceful
    skip of malformed inputs).
    """
    images, labels, names = [], [], []
    subjects = sorted(
        d for d in os.listdir(path) if os.path.isdir(os.path.join(path, d))
    )
    from opencv_facerecognizer_tpu.utils import native

    for subject in subjects:
        subject_dir = os.path.join(path, subject)
        files = sorted(os.listdir(subject_dir))
        # Label assigned from the names list so a subject dir with zero
        # readable images cannot shift later subjects onto wrong names.
        label = len(names)
        count = 0
        paths = [os.path.join(subject_dir, fn) for fn in files]
        native_ok = np.zeros((len(paths),), bool)
        batch = None
        if image_size is not None and native.available():
            # Fast path: decode+resize the subject's whole folder into one
            # packed buffer in native code; failures fall back per-file.
            native_paths = [p if native.handles(p) else "" for p in paths]
            if any(native_paths):
                batch, native_ok = native.load_batch(native_paths, image_size)
        for i, p in enumerate(paths):
            if native_ok[i]:
                img = batch[i]
            else:
                img = _imread_gray(p)
                if img is None:
                    continue
                if image_size is not None:
                    img = _resize_gray(img, image_size)
            images.append(img)
            labels.append(label)
            count += 1
        if count:
            names.append(subject)
    if not images:
        raise ValueError(f"no readable images under {path!r}")
    return np.stack(images), np.asarray(labels, dtype=np.int32), names


def shuffle(X: np.ndarray, y: np.ndarray, seed: int = 0):
    """Deterministic joint shuffle (the reference's shuffle util)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y))
    if isinstance(X, list):
        return [X[i] for i in perm], np.asarray(y)[perm]
    return np.asarray(X)[perm], np.asarray(y)[perm]


def _bilinear_sample(img: np.ndarray, ys: np.ndarray, xs: np.ndarray) -> np.ndarray:
    """Edge-clamped bilinear sampling of ``img`` at float coords (ys, xs)."""
    h, w = img.shape
    ys = np.clip(ys, 0.0, h - 1.0)
    xs = np.clip(xs, 0.0, w - 1.0)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    fy = (ys - y0).astype(np.float32)
    fx = (xs - x0).astype(np.float32)
    return (img[y0, x0] * (1 - fy) * (1 - fx)
            + img[y1, x0] * fy * (1 - fx)
            + img[y0, x1] * (1 - fy) * fx
            + img[y1, x1] * fy * fx)


def _smooth_field(rng: np.random.Generator, shape: Tuple[int, int],
                  amplitude: float, cells: int = 8) -> np.ndarray:
    """Low-frequency random displacement field: coarse noise, kron-upsampled
    and box-blurred twice — smooth enough to read as pose/expression
    deformation rather than pixel noise."""
    h, w = shape
    coarse = rng.normal(scale=amplitude, size=(-(-h // cells), -(-w // cells)))
    field = np.kron(coarse, np.ones((cells, cells)))[:h, :w]
    for _ in range(2):  # separable 3x3 box blur, edge-padded
        field = (np.pad(field, 1, mode="edge")[:-2, 1:-1]
                 + field + np.pad(field, 1, mode="edge")[2:, 1:-1]) / 3.0
        field = (np.pad(field, 1, mode="edge")[1:-1, :-2]
                 + field + np.pad(field, 1, mode="edge")[1:-1, 2:]) / 3.0
    return field.astype(np.float32)


def make_synthetic_faces(
    num_subjects: int = 10,
    per_subject: int = 10,
    size: Tuple[int, int] = (32, 32),
    seed: int = 0,
    noise: float = 12.0,
    illumination: float = 0.35,
    rotation: float = 0.0,
    scale_jitter: float = 0.0,
    elastic: float = 0.0,
    occlusion: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Deterministic face-like dataset: per-subject smooth base pattern +
    per-sample noise, global illumination scaling, and small translations —
    the variation axes the classic pipeline (TanTriggs/PCA/LDA/LBP) exists
    to handle. Returns (images [N,H,W] in [0,255], labels, names).

    The hard-protocol axes (all default-off so existing distributions stay
    bit-identical; the round-2 verdict asked for a protocol "worth 99%"):

    - ``rotation``: per-sample in-plane pose rotation, uniform in
      [-rotation, +rotation] degrees, bilinear resample around the center.
    - ``scale_jitter``: per-sample scale factor uniform in [1-s, 1+s]
      (composed into the same affine warp).
    - ``elastic``: per-sample smooth elastic deformation, displacement
      amplitude in pixels (low-frequency field — expression/3-D pose
      analog, the deformation PCA/LDA templates cannot model linearly).
    - ``occlusion``: probability of one random occluding rectangle
      (20-45% of each side, filled with flat gray + noise — sunglasses/
      scarf analog).
    """
    rng = np.random.default_rng(seed)
    h, w = size
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    cy0, cx0 = (h - 1) / 2.0, (w - 1) / 2.0
    images, labels = [], []
    for s in range(num_subjects):
        # Smooth "identity" structure: sum of a few random low-freq gaussians.
        base = np.zeros((h, w), dtype=np.float32)
        for _ in range(6):
            cy, cx = rng.uniform(0, h), rng.uniform(0, w)
            sy, sx = rng.uniform(h / 8, h / 3), rng.uniform(w / 8, w / 3)
            amp = rng.uniform(-1.0, 1.0)
            base += amp * np.exp(-(((yy - cy) / sy) ** 2 + ((xx - cx) / sx) ** 2))
        base = 128.0 + 90.0 * base / (np.abs(base).max() + 1e-6)
        for _ in range(per_subject):
            img = base.copy()
            # small translation (integer, wraps cropped)
            ty, tx = rng.integers(-2, 3, size=2)
            if rotation or scale_jitter or elastic:
                # One composed inverse-map warp: rotate + scale about the
                # center, translate, plus the elastic displacement field.
                ang = np.deg2rad(rng.uniform(-rotation, rotation)) if rotation else 0.0
                sc = rng.uniform(1 - scale_jitter, 1 + scale_jitter) if scale_jitter else 1.0
                cos_a, sin_a = np.cos(ang), np.sin(ang)
                y0 = yy - cy0 - ty
                x0 = xx - cx0 - tx
                ys = (cos_a * y0 + sin_a * x0) / sc + cy0
                xs = (-sin_a * y0 + cos_a * x0) / sc + cx0
                if elastic:
                    ys = ys + _smooth_field(rng, (h, w), elastic)
                    xs = xs + _smooth_field(rng, (h, w), elastic)
                img = _bilinear_sample(img, ys, xs)
            else:
                img = np.roll(img, (ty, tx), axis=(0, 1))
            if occlusion and rng.uniform() < occlusion:
                oh = int(rng.uniform(0.20, 0.45) * h)
                ow = int(rng.uniform(0.20, 0.45) * w)
                oy = int(rng.integers(0, h - oh + 1))
                ox = int(rng.integers(0, w - ow + 1))
                patch = rng.uniform(40, 200) + rng.normal(
                    scale=8.0, size=(oh, ow)).astype(np.float32)
                img[oy : oy + oh, ox : ox + ow] = patch
            # illumination scale + offset
            img = img * rng.uniform(1 - illumination, 1 + illumination) + rng.uniform(-20, 20)
            img = img + rng.normal(scale=noise, size=(h, w))
            images.append(np.clip(img, 0, 255).astype(np.float32))
            labels.append(s)
    names = [f"subject_{i:02d}" for i in range(num_subjects)]
    return np.stack(images), np.asarray(labels, dtype=np.int32), names


def make_synthetic_scenes(
    num_scenes: int = 32,
    scene_size: Tuple[int, int] = (96, 96),
    max_faces: int = 3,
    face_size_range: Tuple[int, int] = (20, 36),
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Detection-training scenes: textured background with 0..max_faces
    bright ellipse-masked "face" patches pasted in (distinct enough for a
    small detector to learn). Returns (scenes [N,H,W] in [0,255],
    boxes [N,max_faces,4] pixel yxyx zero-padded, num_faces [N])."""
    rng = np.random.default_rng(seed)
    h, w = scene_size
    scenes = np.zeros((num_scenes, h, w), dtype=np.float32)
    boxes = np.zeros((num_scenes, max_faces, 4), dtype=np.float32)
    counts = np.zeros((num_scenes,), dtype=np.int32)
    for i in range(num_scenes):
        # low-frequency background texture (kron-upsampled, cropped to size)
        bg = rng.normal(scale=1.0, size=(-(-h // 8), -(-w // 8))).astype(np.float32)
        bg = np.kron(bg, np.ones((8, 8), dtype=np.float32))[:h, :w]
        scene = 80.0 + 20.0 * bg + rng.normal(scale=6.0, size=(h, w)).astype(np.float32)
        n_faces = int(rng.integers(0, max_faces + 1))
        placed = 0
        attempts = 0
        while placed < n_faces and attempts < 20:
            attempts += 1
            fs = int(rng.integers(face_size_range[0], face_size_range[1] + 1))
            y0 = int(rng.integers(0, h - fs + 1))
            x0 = int(rng.integers(0, w - fs + 1))
            # reject overlaps with already-placed boxes
            ok = True
            for b in range(placed):
                by0, bx0, by1, bx1 = boxes[i, b]
                if not (y0 + fs < by0 or by1 < y0 or x0 + fs < bx0 or bx1 < x0):
                    ok = False
                    break
            if not ok:
                continue
            yy, xx = np.mgrid[0:fs, 0:fs].astype(np.float32)
            cy, cx = fs / 2, fs / 2
            ellipse = (((yy - cy) / (fs * 0.5)) ** 2 + ((xx - cx) / (fs * 0.42)) ** 2) <= 1.0
            face = 190.0 + 30.0 * np.cos(yy / fs * 3.1) + rng.normal(scale=8.0, size=(fs, fs))
            # darker "eyes" structure so faces are not plain blobs
            for ex in (0.32, 0.68):
                eyy, exx = int(fs * 0.38), int(fs * ex)
                rr = max(1, fs // 10)
                face[eyy - rr : eyy + rr, exx - rr : exx + rr] -= 90.0
            region = scene[y0 : y0 + fs, x0 : x0 + fs]
            region[ellipse] = face[ellipse]
            boxes[i, placed] = (y0, x0, y0 + fs, x0 + fs)
            placed += 1
        counts[i] = placed
        scenes[i] = np.clip(scene, 0, 255)
    return scenes, boxes, counts
