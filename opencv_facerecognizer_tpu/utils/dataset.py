"""Dataset utilities (SURVEY.md §2.1 "Matrix/dataset utils").

``read_images`` walks the reference-family dataset layout (one folder per
subject containing face images) and returns (images [N, H, W] float32,
labels [N] int, subject_names). Decoding uses cv2 when present, else PIL —
both are host-side I/O; everything downstream is device arrays.

``make_synthetic_faces`` generates a deterministic ORL-like dataset (distinct
per-subject structure + per-sample noise/illumination) so the validation
harness and tests run without network access to the real AT&T/LFW data
(the environment has zero egress — SURVEY.md §0).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np


def _imread_gray(path: str) -> Optional[np.ndarray]:
    try:
        import cv2

        img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
        return None if img is None else img.astype(np.float32)
    except ImportError:
        pass
    try:
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("L"), dtype=np.float32)
    except Exception:
        return None


def read_images(
    path: str, image_size: Optional[Tuple[int, int]] = None
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Walk ``path/<subject>/<image files>`` -> (images, labels, names).

    Subjects are sorted for determinism; unreadable files are skipped with a
    warning count rather than aborting enrolment (SURVEY.md §5.3 graceful
    skip of malformed inputs).
    """
    images, labels, names = [], [], []
    subjects = sorted(
        d for d in os.listdir(path) if os.path.isdir(os.path.join(path, d))
    )
    for subject in subjects:
        subject_dir = os.path.join(path, subject)
        files = sorted(os.listdir(subject_dir))
        # Label assigned from the names list so a subject dir with zero
        # readable images cannot shift later subjects onto wrong names.
        label = len(names)
        count = 0
        for fn in files:
            img = _imread_gray(os.path.join(subject_dir, fn))
            if img is None:
                continue
            if image_size is not None:
                import cv2

                img = cv2.resize(img, (image_size[1], image_size[0])).astype(np.float32)
            images.append(img)
            labels.append(label)
            count += 1
        if count:
            names.append(subject)
    if not images:
        raise ValueError(f"no readable images under {path!r}")
    return np.stack(images), np.asarray(labels, dtype=np.int32), names


def shuffle(X: np.ndarray, y: np.ndarray, seed: int = 0):
    """Deterministic joint shuffle (the reference's shuffle util)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y))
    if isinstance(X, list):
        return [X[i] for i in perm], np.asarray(y)[perm]
    return np.asarray(X)[perm], np.asarray(y)[perm]


def make_synthetic_faces(
    num_subjects: int = 10,
    per_subject: int = 10,
    size: Tuple[int, int] = (32, 32),
    seed: int = 0,
    noise: float = 12.0,
    illumination: float = 0.35,
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Deterministic face-like dataset: per-subject smooth base pattern +
    per-sample noise, global illumination scaling, and small translations —
    the variation axes the classic pipeline (TanTriggs/PCA/LDA/LBP) exists
    to handle. Returns (images [N,H,W] in [0,255], labels, names)."""
    rng = np.random.default_rng(seed)
    h, w = size
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    images, labels = [], []
    for s in range(num_subjects):
        # Smooth "identity" structure: sum of a few random low-freq gaussians.
        base = np.zeros((h, w), dtype=np.float32)
        for _ in range(6):
            cy, cx = rng.uniform(0, h), rng.uniform(0, w)
            sy, sx = rng.uniform(h / 8, h / 3), rng.uniform(w / 8, w / 3)
            amp = rng.uniform(-1.0, 1.0)
            base += amp * np.exp(-(((yy - cy) / sy) ** 2 + ((xx - cx) / sx) ** 2))
        base = 128.0 + 90.0 * base / (np.abs(base).max() + 1e-6)
        for _ in range(per_subject):
            img = base.copy()
            # small translation (integer, wraps cropped)
            ty, tx = rng.integers(-2, 3, size=2)
            img = np.roll(img, (ty, tx), axis=(0, 1))
            # illumination scale + offset
            img = img * rng.uniform(1 - illumination, 1 + illumination) + rng.uniform(-20, 20)
            img = img + rng.normal(scale=noise, size=(h, w))
            images.append(np.clip(img, 0, 255).astype(np.float32))
            labels.append(s)
    names = [f"subject_{i:02d}" for i in range(num_subjects)]
    return np.stack(images), np.asarray(labels, dtype=np.int32), names
