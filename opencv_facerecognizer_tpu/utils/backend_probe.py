"""Deadline-bounded usability probe of the default JAX backend.

The tunneled TPU backend on this machine has two distinct failure modes
(both observed during the round-4 outage):

1. **fast-fail** — backend init raises ``UNAVAILABLE`` immediately;
2. **hang-mode** — ``jax.devices()`` blocks forever (the local relay
   accepts the TCP connection but the pool side never answers).

Mode 2 is the dangerous one: any probe that touches backend init *in the
calling process* inherits the hang, so a CPU-only dryrun that merely wanted
to ask "is the real backend usable?" dies by timeout behind a dead TPU it
never needed. The fix is structural: the probe runs in a **subprocess with
a deadline**. The child is the only process that risks backend init; if it
hangs, it is killed at the deadline and the caller falls back cleanly.

Used by ``__graft_entry__.dryrun_multichip`` (multi-chip validation must be
producible with the accelerator unplugged) and ``bench.py`` (a dead backend
yields a structured fast-fail artifact, not a 10-minute hang + traceback).
Mirrors the reference's failure-detection posture (SURVEY.md §5.3): health
checks are bounded, and an unhealthy accelerator degrades the job, never
wedges it.
"""

from __future__ import annotations

import os
import subprocess
import sys

# Hard override: skip the probe entirely and report the backend unusable,
# sending callers straight to their CPU fallback. For driver/ops use when
# the backend is known-dead and even the bounded probe's deadline is
# unwanted latency. Deliberately affects EVERY probe consumer: the dryrun
# falls back to virtual CPU devices, and bench.py / the measurement queue
# fast-fail with this env var named in the artifact's reason field — a TPU
# benchmark under a forced-CPU override would be meaningless, so refusing
# loudly beats measuring the wrong thing.
FORCE_CPU_ENV = "OCVF_DRYRUN_FORCE_CPU"
TIMEOUT_ENV = "OCVF_BACKEND_PROBE_TIMEOUT_S"
# First axon init on a healthy tunnel takes ~10-20 s; 60 s separates
# "slow init" from "hang-mode" with wide margin.
DEFAULT_TIMEOUT_S = 60.0
# Degraded-mode recovery probes (runtime.resilience) run on a SHORTER
# leash: the serving loop is already failing, so a fast verdict beats a
# precise one — 15 s still covers a healthy re-init, and a hang past it is
# exactly the answer the caller needed.
RECOVERY_TIMEOUT_ENV = "OCVF_RECOVERY_PROBE_TIMEOUT_S"
DEFAULT_RECOVERY_TIMEOUT_S = 15.0

# Child exit codes (anything else = init/exec raised).
_RC_OK = 0
_RC_TOO_FEW_DEVICES = 3
_RC_CPU_FALLBACK = 4


def _probe_source(min_devices: int, allow_cpu: bool) -> str:
    """Child source: init the default backend, count devices, run one eager
    op (round-1 driver failure: axon init succeeded but the first op raised
    a libtpu version mismatch — init success alone proves nothing). With
    ``allow_cpu=False`` the child additionally rejects an all-CPU default
    backend: a silent JAX fallback to CPU would otherwise make a dead TPU
    probe as "usable" and a benchmark would quietly measure the wrong
    hardware under a per-chip metric name."""
    lines = [
        "import sys",
        "import jax",
        "import jax.numpy as jnp",
        f"if len(jax.devices()) < {int(min_devices)}:",
        f"    sys.exit({_RC_TOO_FEW_DEVICES})",
    ]
    if not allow_cpu:
        lines += [
            "if all(d.platform == 'cpu' for d in jax.devices()):",
            f"    sys.exit({_RC_CPU_FALLBACK})",
        ]
    lines += [
        "jax.block_until_ready(jnp.zeros((), jnp.float32) + 1)",
        f"sys.exit({_RC_OK})",
    ]
    return "\n".join(lines) + "\n"


def probe_default_backend(
    min_devices: int = 1,
    timeout_s: float | None = None,
    probe_source: str | None = None,
    allow_cpu: bool = True,
) -> tuple[bool, str]:
    """Return ``(usable, reason)`` for the default backend, never hanging.

    ``allow_cpu=False`` rejects an all-CPU default backend (accelerator
    benchmarks); the default tolerates CPU because the dryrun genuinely
    wants whatever default backend has enough devices, including a forced
    host platform. ``probe_source`` overrides the child program (tests
    inject a sleeping child to simulate hang-mode without a dead tunnel).
    """
    if os.environ.get(FORCE_CPU_ENV, "") not in ("", "0"):
        return False, f"{FORCE_CPU_ENV} override set"
    if timeout_s is None:
        try:
            timeout_s = float(os.environ.get(TIMEOUT_ENV, DEFAULT_TIMEOUT_S))
        except ValueError:
            # A config typo must not turn the structured fast-fail into a
            # raw traceback (or eat the queue's wait budget) — fall back.
            timeout_s = DEFAULT_TIMEOUT_S
    source = (probe_source if probe_source is not None
              else _probe_source(min_devices, allow_cpu))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", source],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe exceeded {timeout_s:.0f}s deadline (backend hang-mode)"
    except OSError as exc:
        return False, f"probe spawn failed: {exc}"
    if proc.returncode == _RC_OK:
        return True, "ok"
    if proc.returncode == _RC_TOO_FEW_DEVICES:
        return False, f"backend has fewer than {min_devices} devices"
    if proc.returncode == _RC_CPU_FALLBACK:
        return False, "default backend is CPU (accelerator missing or fell back)"
    return False, f"backend init/first-op failed (probe rc={proc.returncode})"


def probe_for_recovery(timeout_s: float | None = None,
                       probe_source: str | None = None) -> tuple[bool, str]:
    """Degraded-mode backend check for the serving loop (runtime.resilience):
    same bounded subprocess probe, shorter default deadline, and
    ``allow_cpu=False`` — after consecutive dispatch failures the question
    is "is the ACCELERATOR alive?", and a silent JAX fallback to CPU must
    read as dead so the service's CPU-fallback hook (an explicit,
    announced degradation) fires instead of a silent mis-measured one."""
    if timeout_s is None:
        try:
            timeout_s = float(os.environ.get(RECOVERY_TIMEOUT_ENV,
                                             DEFAULT_RECOVERY_TIMEOUT_S))
        except ValueError:
            timeout_s = DEFAULT_RECOVERY_TIMEOUT_S
    return probe_default_backend(timeout_s=timeout_s, allow_cpu=False,
                                 probe_source=probe_source)
