"""Visualization helpers (SURVEY.md §2.1 "Visualization": the reference's
facerec/visual.py plotted eigenfaces/Fisherfaces and the mean face).

Matplotlib is imported lazily so headless/serving deployments never pay for
it; everything renders to a file (no GUI assumptions).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _normalize_for_display(img: np.ndarray) -> np.ndarray:
    img = np.asarray(img, np.float64)
    lo, hi = img.min(), img.max()
    return (img - lo) / (hi - lo) if hi > lo else np.zeros_like(img)


def subplot_grid(
    images: Sequence[np.ndarray],
    titles: Optional[Sequence[str]] = None,
    rows: Optional[int] = None,
    cols: int = 4,
    suptitle: str = "",
    filename: str = "plot.png",
) -> str:
    """Save a grid of grayscale images (the reference's ``subplot`` helper)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    n = len(images)
    cols = min(cols, max(n, 1))
    rows = rows or -(-n // cols)
    fig, axes = plt.subplots(rows, cols, figsize=(2.2 * cols, 2.4 * rows))
    axes = np.atleast_1d(axes).ravel()
    for i, ax in enumerate(axes):
        ax.axis("off")
        if i < n:
            ax.imshow(_normalize_for_display(images[i]), cmap="gray")
            if titles and i < len(titles):
                ax.set_title(str(titles[i]), fontsize=8)
    if suptitle:
        fig.suptitle(suptitle)
    fig.tight_layout()
    fig.savefig(filename, dpi=110)
    plt.close(fig)
    return filename


def plot_eigenfaces(
    feature, image_size, num: int = 8, filename: str = "eigenfaces.png"
) -> str:
    """Render the top subspace components of a fitted PCA/Fisherfaces plugin."""
    comps = np.asarray(feature.eigenvectors)  # [D, K]
    num = min(num, comps.shape[1])
    faces = [comps[:, i].reshape(image_size) for i in range(num)]
    titles = [f"component {i}" for i in range(num)]
    return subplot_grid(faces, titles, suptitle=type(feature).__name__, filename=filename)


def plot_mean_face(feature, image_size, filename: str = "meanface.png") -> str:
    mean = np.asarray(feature.mean).reshape(image_size)
    return subplot_grid([mean], ["mean face"], filename=filename)


def draw_detections(
    frame: np.ndarray, faces: Sequence[dict], filename: str = "detections.png"
) -> str:
    """Overlay recognition results (box + name + similarity) on one frame —
    the file-output equivalent of the reference's draw_str/rectangle overlay."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib import patches

    fig, ax = plt.subplots(figsize=(6, 6 * frame.shape[0] / max(frame.shape[1], 1)))
    ax.imshow(_normalize_for_display(frame), cmap="gray")
    ax.axis("off")
    for face in faces:
        x0, y0, x1, y1 = face["box"]
        ax.add_patch(patches.Rectangle((x0, y0), x1 - x0, y1 - y0,
                                       fill=False, edgecolor="lime", linewidth=1.5))
        ax.text(x0, max(y0 - 3, 0), f"{face.get('name', '?')} {face.get('similarity', 0):.2f}",
                color="lime", fontsize=8, va="bottom")
    fig.tight_layout()
    fig.savefig(filename, dpi=110)
    plt.close(fig)
    return filename
