"""Validation harness (SURVEY.md §1 L5, §3.5): the reference's de-facto
correctness machinery — KFold / LeaveOneOut / Simple validation producing
``ValidationResult`` accuracy records.

TPU-first notes: each fold refits the model (data-dependent gallery sizes),
so folds run as a host loop; *within* a fold, fit and the whole test batch
predict are single device computations — the reference's per-sample predict
loop (SURVEY.md §3.5) is gone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np


@dataclass
class ValidationResult:
    true_positives: int = 0
    false_positives: int = 0
    description: str = ""

    @property
    def total(self) -> int:
        return self.true_positives + self.false_positives

    @property
    def accuracy(self) -> float:
        return self.true_positives / self.total if self.total else float("nan")

    def __repr__(self):
        return (
            f"ValidationResult(acc={self.accuracy:.4f}, "
            f"tp={self.true_positives}, fp={self.false_positives}, "
            f"desc={self.description!r})"
        )


def precision(true_positives: int, false_positives: int) -> float:
    total = true_positives + false_positives
    return true_positives / total if total else float("nan")


def accuracy(true_positives: int, false_positives: int) -> float:
    return precision(true_positives, false_positives)


@dataclass
class ValidationStrategy:
    """Base: subclasses implement ``validate(model, X, y)`` appending
    ValidationResults to ``self.results``."""

    results: List[ValidationResult] = field(default_factory=list)

    def validate(self, model, X, y):
        raise NotImplementedError

    @property
    def mean_accuracy(self) -> float:
        accs = [r.accuracy for r in self.results if r.total]
        return float(np.mean(accs)) if accs else float("nan")

    def _score_fold(self, model, X_train, y_train, X_test, y_test, desc: str):
        model.compute(X_train, y_train)
        pred, _ = model.predict(np.asarray(X_test))
        pred = np.asarray(pred)
        tp = int(np.sum(pred == np.asarray(y_test)))
        result = ValidationResult(
            true_positives=tp, false_positives=len(y_test) - tp, description=desc
        )
        self.results.append(result)
        return result


def stratified_kfold_indices(y: np.ndarray, k: int, seed: int = 0) -> List[np.ndarray]:
    """Label-stratified fold index lists (SURVEY.md §3.5)."""
    rng = np.random.default_rng(seed)
    y = np.asarray(y)
    folds: List[list] = [[] for _ in range(k)]
    for cls in np.unique(y):
        idx = np.flatnonzero(y == cls)
        rng.shuffle(idx)
        for i, j in enumerate(idx):
            folds[i % k].append(j)
    return [np.asarray(sorted(f), dtype=np.int64) for f in folds]


@dataclass
class KFoldCrossValidation(ValidationStrategy):
    k: int = 10
    seed: int = 0

    def validate(self, model, X, y):
        X = np.asarray(X)
        y = np.asarray(y)
        folds = stratified_kfold_indices(y, self.k, self.seed)
        for i, test_idx in enumerate(folds):
            if len(test_idx) == 0:
                continue
            train_mask = np.ones(len(y), dtype=bool)
            train_mask[test_idx] = False
            self._score_fold(
                model,
                X[train_mask],
                y[train_mask],
                X[test_idx],
                y[test_idx],
                desc=f"fold {i + 1}/{self.k}",
            )
        return self


@dataclass
class LeaveOneOutCrossValidation(ValidationStrategy):
    def validate(self, model, X, y):
        X = np.asarray(X)
        y = np.asarray(y)
        for i in range(len(y)):
            mask = np.ones(len(y), dtype=bool)
            mask[i] = False
            self._score_fold(
                model, X[mask], y[mask], X[i : i + 1], y[i : i + 1], desc=f"leave-out {i}"
            )
        return self


@dataclass
class SimpleValidation(ValidationStrategy):
    """Fit and score on given train/test split (or same data if no split)."""

    def validate(self, model, X, y, X_test=None, y_test=None):
        X = np.asarray(X)
        y = np.asarray(y)
        X_test = X if X_test is None else np.asarray(X_test)
        y_test = y if y_test is None else np.asarray(y_test)
        self._score_fold(model, X, y, X_test, y_test, desc="simple")
        return self
