"""Frame-lifecycle tracing: causal spans + flight recorder (observability
layer).

Before this module, a frame that vanished left behind aggregate counters
(``utils/metrics.py``) and nothing else — nobody could answer "what
happened to frame 48123" or "what was in flight when the soak wedged".
This layer records one causal **span** per stage a frame passes through:

    receive (verdict) -> queue_wait (batch ancestry) -> [batch trace:
    dispatch / ready_wait / publish] -> settle (terminal outcome)

plus **lifecycle spans** for the slow machinery (checkpoints, WAL appends,
IVF retrains, brownout transitions, recovery). Spans are plain dicts held
in **per-topic bounded ring buffers** — a flight recorder, not an archive:

- **Emission is lock-free.** ``collections.deque`` appends are documented
  thread-safe in CPython, so the hot path (connector thread, serving loop,
  readback worker) never takes a lock to record a span; the tracer's
  ``_lock`` guards only ring *creation* and dump bookkeeping, and never
  nests inside (or around) any serving-path lock.
- **Sampling is deterministic.** The per-trace keep/drop verdict is a pure
  function of ``(seed, frame arrival index)`` (a Knuth multiplicative hash
  over frame-trace ids, which have their own counter — span emission and
  batch/lifecycle traces can never shift them), so a replayed chaos run
  with the logged seed samples exactly the same frames whenever the frame
  arrival order itself replays. ``sample=1.0`` traces everything — the
  mode the chaos accounting check runs in; lifecycle and batch spans are
  never sampled out.
- **Terminal accounting.** Every admitted frame must end in exactly one
  ``settle`` span whose ``outcome`` is either ``"completed"`` or the
  ledger drop-counter name it was counted under — the span-level mirror of
  the admission-ledger invariant ``admitted == completed + Σ drops``.
  ``account_spans`` reduces a span list back to that ledger shape so the
  chaos soak can cross-check them exactly.
- **Flight recorder.** ``dump()`` writes the rings atomically
  (``atomic_write_json`` — a crash mid-dump never leaves a torn file) to
  ``dump_dir/flight-<seq>-<reason>.json`` with bounded retention, on wedge
  detection, supervisor restart, SIGTERM drain, and dead-letter. Span
  timestamps are ``time.monotonic()``; each dump header carries paired
  monotonic + wall clocks so offline readers can convert.
- **JSONL export.** An optional ``span_sink`` (a ``RotatingJournal`` from
  ``make_span_journal``, sharing the dead-letter journal's bounded
  rotating machinery) streams every emitted span as one JSON line — for
  offline analysis beyond the ring's horizon. Off by default: it adds a
  file write per span, which is what the sampling knob is for.

Overhead: one dict + one deque append per span, ~3 spans per frame at
``sample=1.0``. The bench gate (``bench_serving.py --smoke`` section
``tracing_overhead``) holds the fully-enabled e2e p50 regression under 3%.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

#: ring topic for batch-level spans (dispatch / ready_wait / publish /
#: dead_letter); frame spans ride the topic the frame arrived on.
BATCH_TOPIC = "_batch"
#: ring topic for lifecycle spans (checkpoint / wal_append / ivf_retrain /
#: brownout / recover ...).
LIFECYCLE_TOPIC = "_lifecycle"
#: the terminal span stage every admitted frame must reach exactly once.
SETTLE_STAGE = "settle"
#: ``settle`` outcome of a frame that published a result; every other
#: outcome is the admission-ledger drop-counter name it was counted under.
OUTCOME_COMPLETED = "completed"
#: ``settle`` outcome of a frame the stage-1 cascade rejected as
#: face-free: published with an empty face list, never dispatched to the
#: full detector — the ledger's ``completed_empty`` terminal status, a
#: sibling of completed, not a drop.
OUTCOME_COMPLETED_EMPTY = "completed_empty"
#: ``settle`` outcome of a frame answered FROM the temporal identity
#: cache (ISSUE 17): published with the cached identities, never
#: dispatched — the ledger's ``completed_cached`` terminal status, a
#: sibling of completed/completed_empty, not a drop.
OUTCOME_COMPLETED_CACHED = "completed_cached"

_HASH_MULT = 2654435761  # Knuth multiplicative hash (mod 2^32)


class Tracer:
    """Per-topic span ring buffers with deterministic sampling and an
    atomic flight-recorder dump (module docstring)."""

    def __init__(self, ring_size: int = 4096, sample: float = 1.0,
                 seed: int = 0, dump_dir: Optional[str] = None,
                 keep_dumps: int = 8, min_dump_interval_s: float = 1.0,
                 span_sink=None, metrics=None, fault_injector=None):
        self.ring_size = max(1, int(ring_size))
        self.sample = min(1.0, max(0.0, float(sample)))
        self.seed = int(seed)
        self.dump_dir = None if dump_dir is None else str(dump_dir)
        self.keep_dumps = max(1, int(keep_dumps))
        self.min_dump_interval_s = float(min_dump_interval_s)
        #: optional RotatingJournal-shaped sink (``append_line``) streaming
        #: every span as JSONL; non-strict — a sink failure never raises
        #: into the serving path (the journal counts its own errors).
        self.span_sink = span_sink
        #: optional shared Metrics surface for DUMP accounting only — span
        #: emission deliberately never touches the Metrics lock.
        self.metrics = metrics
        #: chaos hook (runtime.faults): the ``storage`` boundary fires
        #: inside ``dump`` before the atomic write, so an injected
        #: ENOSPC/EIO exercises the exact counted never-raise path a full
        #: disk does. None in production.
        self.fault_injector = fault_injector
        #: degraded-durability shed hook: while truthy, dumps are dropped
        #: before touching the disk (counted ``trace_dumps_shed``) — the
        #: flight recorder must never contend with the WAL for a dying
        #: disk's last bytes. Wired by DurabilityMonitor.attach_sinks.
        self.shed_fn = None
        # THREE id streams (next() on each is atomic in CPython):
        # - frame-trace ids (ODD): drawn in frame-arrival order ONLY, so
        #   the sampling verdict for "the Nth arriving frame" is a pure
        #   function of (seed, N) — batch/lifecycle traces and span
        #   emission (whose interleaving is thread-timing dependent) must
        #   not shift it between replayed runs;
        # - batch/lifecycle trace ids (EVEN): disjoint from frame ids so
        #   the two families can never collide in one span stream;
        # - span ids: a global emission-order sequence for sorting only.
        self._frame_ids = itertools.count(0)
        self._aux_ids = itertools.count(1)
        self._span_ids = itertools.count(1)
        self._rings: Dict[str, deque] = {}
        # Guards ring creation + dump bookkeeping ONLY; never held across
        # emission, file I/O, or any call out of this class.
        self._lock = threading.Lock()
        self._dump_seq = itertools.count(1)
        self._last_dump_t: Dict[str, float] = {}
        if self.dump_dir is not None:
            os.makedirs(self.dump_dir, exist_ok=True)

    # ---- trace ids + sampling ----

    def start_trace(self, topic: str) -> int:
        """New frame trace id (odd), or 0 when sampled out (every ``emit``
        with trace id 0 is a no-op — the whole frame records nothing).
        Deterministic: the verdict is a pure function of (seed, arrival
        index) — frame ids come from their own counter, so concurrent
        span emission or batch/lifecycle traces can never shift which
        frames a replayed run samples (replay determinism then only needs
        the frame ARRIVAL order itself to be deterministic)."""
        tid = 2 * next(self._frame_ids) + 1
        if self.sample >= 1.0:
            return tid
        if self.sample <= 0.0:
            return 0
        h = ((tid + self.seed) * _HASH_MULT) & 0xFFFFFFFF
        return tid if h < self.sample * 4294967296.0 else 0

    def new_trace(self) -> int:
        """Unconditional trace id (even) for batch/lifecycle traces —
        never sampled out (they are few and carry the causal ancestry),
        and disjoint from the frame-trace id space."""
        return 2 * next(self._aux_ids)

    # ---- emission (the hot path: no locks) ----

    def _ring_for(self, topic: str) -> deque:
        ring = self._rings.get(topic)
        if ring is None:
            with self._lock:  # first span on a topic only
                ring = self._rings.setdefault(
                    topic, deque(maxlen=self.ring_size))
        return ring

    def emit(self, trace_id: int, stage: str, topic: Optional[str] = None,
             t0: Optional[float] = None, dur: float = 0.0,
             **attrs: Any) -> None:
        """Record one finished span. ``t0`` is ``time.monotonic()`` at
        span start (defaults to now - dur); ``dur`` seconds. No-op for
        trace id 0 (sampled out). Lock-free: one dict + one thread-safe
        deque append."""
        if not trace_id:
            return
        span: Dict[str, Any] = {
            "trace": trace_id,
            "span": next(self._span_ids),
            "stage": stage,
            "t0": (time.monotonic() - dur) if t0 is None else t0,
            "dur": dur,
        }
        if attrs:
            span.update(attrs)
        self._ring_for(topic or BATCH_TOPIC).append(span)
        sink = self.span_sink
        if sink is not None:
            sink.append_line(json.dumps({"topic": topic or BATCH_TOPIC,
                                         **span}, default=repr))

    @contextlib.contextmanager
    def lifecycle(self, stage: str, **attrs: Any):
        """Span a lifecycle operation: yields a mutable attr dict the
        body may enrich; the span is emitted on exit with the measured
        duration, ``ok`` False plus the error repr when the body raised
        (re-raised).

        Use this when the spanned body holds NO locks at exit. The
        runtime's own lifecycle sites (WAL append, checkpoint, IVF
        retrain) deliberately hand-roll the same t0/outcome/finally
        pattern instead: their emission must fire strictly AFTER their
        guard locks release — with a ``span_sink`` wired, ``emit`` does
        file I/O, and I/O under ``_enroll_lock``/``_ckpt_lock``/
        ``_train_lock`` is exactly what the blocking-under-lock
        discipline forbids."""
        tid = self.new_trace()
        t0 = time.monotonic()
        try:
            yield attrs
        except BaseException as exc:
            attrs.setdefault("ok", False)
            attrs.setdefault("error", repr(exc))
            raise
        finally:
            attrs.setdefault("ok", True)
            self.emit(tid, stage, topic=LIFECYCLE_TOPIC, t0=t0,
                      dur=time.monotonic() - t0, **attrs)

    # ---- reading ----

    def topics(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def snapshot(self, topic: Optional[str] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Spans currently held (oldest first), one topic or all merged in
        emission order. Emission is lock-free, so a concurrent append can
        interrupt iteration (CPython raises RuntimeError) — retry a few
        times rather than serialize the hot path against readers."""
        if topic is not None:
            rings = [self._rings.get(topic)]
        else:
            with self._lock:
                rings = list(self._rings.values())
        out: List[Dict[str, Any]] = []
        for ring in rings:
            if ring is None:
                continue
            for _ in range(8):
                try:
                    # Copy into a TEMP list first: a RuntimeError mid-extend
                    # would otherwise leave a partial copy in ``out`` and
                    # the retry would append the whole ring again —
                    # duplicated spans that break dump accounting.
                    copied = list(ring)
                except RuntimeError:
                    continue  # ring mutated mid-iteration: retry
                out.extend(copied)
                break
        if topic is None:
            out.sort(key=lambda s: s["span"])
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            per_topic = {t: len(r) for t, r in self._rings.items()}
        return {"ring_size": self.ring_size, "sample": self.sample,
                "spans_held": per_topic}

    # ---- the flight recorder ----

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None,
             force: bool = False) -> Optional[str]:
        """Write the current rings atomically to ``dump_dir`` as
        ``flight-<seq>-<reason>.json``; returns the path, or None when no
        dump dir is configured or the per-reason rate limit suppressed it
        (``force`` bypasses the limit — the end-of-run / SIGTERM dumps
        must always land). Retention keeps the newest ``keep_dumps``
        files. Never raises: a recorder failure is counted
        (``trace_dump_errors``) — observability must not hurt serving.
        While the ``shed_fn`` hook reports degraded durability the dump
        is SHED before any I/O (``trace_dumps_shed``, exact accounting;
        ``force`` does not override — a forced dump against a disk known
        broken is still a doomed write competing with the WAL)."""
        if self.dump_dir is None:
            return None
        if self.shed_fn is not None and self.shed_fn():
            if self.metrics is not None:
                self.metrics.incr(mn.TRACE_DUMPS_SHED)
            return None
        now = time.monotonic()
        with self._lock:
            if (not force and self.min_dump_interval_s > 0
                    and now - self._last_dump_t.get(reason, float("-inf"))
                    < self.min_dump_interval_s):
                return None
            self._last_dump_t[reason] = now
            seq = next(self._dump_seq)
        record = {
            "schema": 1,
            "reason": str(reason),
            "seq": seq,
            "ts_unix": time.time(),
            "ts_monotonic": now,
            "sample": self.sample,
            "spans": {t: self.snapshot(t) for t in self.topics()},
        }
        if extra:
            record["extra"] = extra
        path = os.path.join(self.dump_dir, f"flight-{seq:06d}-{reason}.json")
        try:
            if self.fault_injector is not None:
                self.fault_injector.on_storage("trace_dump")
            atomic_write_json(path, record)
        except (OSError, TypeError, ValueError):
            if self.metrics is not None:
                self.metrics.incr(mn.TRACE_DUMP_ERRORS)
            return None
        if self.metrics is not None:
            self.metrics.incr(mn.TRACE_DUMPS)
        self._prune_dumps()
        return path

    def _prune_dumps(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.dump_dir)
                           if n.startswith("flight-") and n.endswith(".json"))
        except OSError:
            return
        for name in names[:-self.keep_dumps or None]:
            try:
                os.remove(os.path.join(self.dump_dir, name))
            except OSError:
                pass


# ---- helpers ----


def make_span_journal(path: str, max_bytes: int = 16 << 20,
                      backups: int = 2, metrics=None, fault_injector=None):
    """A bounded rotating JSONL sink for ``Tracer(span_sink=...)`` — the
    dead-letter journal's ``RotatingJournal`` base reused for span export
    (non-strict appends: a full disk costs spans, never serving; write
    failures and degraded-mode sheds land on the sink's OWN counters,
    ``trace_span_errors``/``trace_spans_shed``, so triage never confuses
    a dying span sink with a dying dead-letter journal).
    Imported lazily so utils keeps no module-level dependency on the
    runtime package."""
    from opencv_facerecognizer_tpu.runtime.journal import RotatingJournal

    return RotatingJournal(path, max_bytes=max_bytes, backups=backups,
                           metrics=metrics, fsync="never",
                           fault_injector=fault_injector,
                           error_counter=mn.TRACE_SPAN_ERRORS,
                           shed_counter=mn.TRACE_SPANS_SHED)


def account_spans(spans: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Reduce frame spans to admission-ledger shape: ``completed`` count +
    per-outcome ``drops`` from the terminal ``settle`` spans, plus
    ``traced`` (distinct traces that emitted a ``receive`` span with an
    admitted verdict). With ``sample=1.0`` these must equal the service's
    ``ledger()`` exactly — the chaos soak's span-accounting check."""
    completed = 0
    completed_empty = 0
    completed_cached = 0
    drops: Dict[str, int] = {}
    admitted_traces = set()
    for span in spans:
        stage = span.get("stage")
        if stage == "receive" and span.get("verdict") == "admitted":
            admitted_traces.add(span.get("trace"))
        elif stage == SETTLE_STAGE:
            outcome = span.get("outcome")
            if outcome == OUTCOME_COMPLETED:
                completed += 1
            elif outcome == OUTCOME_COMPLETED_EMPTY:
                # Cascade early exits are terminal completions, not drops
                # — mirrored as their own ledger bucket.
                completed_empty += 1
            elif outcome == OUTCOME_COMPLETED_CACHED:
                # Track-cache exits (ISSUE 17): same terminal-completion
                # treatment, own bucket.
                completed_cached += 1
            elif outcome:
                drops[outcome] = drops.get(outcome, 0) + 1
    return {"traced": len(admitted_traces), "completed": completed,
            "completed_empty": completed_empty,
            "completed_cached": completed_cached, "drops": drops}


def device_busy_fraction(batch_spans: Iterable[Dict[str, Any]],
                         window_s: float = 30.0,
                         now: Optional[float] = None) -> float:
    """Fraction of the trailing ``window_s`` the device spent on batch
    round-trips, from ``ready_wait`` spans: the union of their
    ``[t0, t0+dur]`` intervals over the window — the same interval-union
    technique ``scripts/trace_summary.py`` applies to device trace lines,
    fed from live spans instead of an offline xplane capture. Overlapping
    in-flight batches are not double-counted."""
    now = time.monotonic() if now is None else now
    lo = now - window_s
    ivals = sorted(
        (max(s["t0"], lo), min(s["t0"] + s["dur"], now))
        for s in batch_spans
        if s.get("stage") == "ready_wait" and s["t0"] + s["dur"] > lo)
    busy = 0.0
    cur_s = cur_e = None
    for s, e in ivals:
        if e <= s:
            continue
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                busy += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        busy += cur_e - cur_s
    return busy / window_s if window_s > 0 else 0.0
