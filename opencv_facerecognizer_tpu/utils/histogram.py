"""Streaming latency histograms: fixed log-bucket counts with a ring of
time-sliced windows (signals layer, under ``utils.metrics``).

Before this module, every latency window in ``utils/metrics.py`` was a
bounded deque of raw samples: percentiles required a sort per read, the
window's horizon was "the last N observations" (whatever wall-clock span
that happened to cover), and between ``reset_window()`` calls the deques
were the only thing bounding memory.  The SLO layer (``runtime/slo.py``)
needs something stronger: *true rolling* quantiles over explicit short and
long horizons, cheap enough to read on every evaluation, at memory that
does not grow with traffic.

Two classes provide it:

- ``LogBucketHistogram`` — counts over a FIXED log-spaced boundary schema
  (shared module-wide so histograms are mergeable by plain count
  addition).  ``observe`` is O(1) (one ``math.log`` + one increment);
  ``quantile``/``fraction_above`` walk the ~100-bucket counts array.  The
  price is resolution: a reported quantile is exact only to its bucket —
  every consumer contract in this repo says "within one bucket width",
  and the property test in ``tests/test_signals.py`` holds the
  implementation to exactly that.
- ``RollingHistogram`` — a ring of ``slices`` time-sliced
  ``LogBucketHistogram``s covering ``window_s`` seconds.  An observation
  lands in the current slice; a read merges the slices still inside the
  requested horizon (short horizons read a suffix of the ring, the full
  window reads all of it).  Expiry is lazy — a slice whose epoch has
  rotated out is simply skipped on read and recycled on the next write —
  so reads never mutate and writes never scan.

Memory per metric window is ``slices x len(BUCKET_BOUNDS)`` integers,
flat forever — the 100k-observation soak test asserts it.  No numpy: this
sits under ``Metrics`` on the serving hot path and must import nothing
heavier than ``math``.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Tuple

#: Log-bucket schema shared by every histogram in the process (merging
#: requires identical boundaries).  Spans 10 us .. 120 s with a 2**0.25
#: growth factor (~19% relative bucket width — four buckets per octave),
#: which covers everything from a sub-ms dispatch to a wedged two-minute
#: readback.  Bucket 0 is the underflow bucket (<= BUCKET_LO); the last
#: bucket is the overflow bucket (> BUCKET_HI).
BUCKET_LO = 1e-5
BUCKET_HI = 120.0
BUCKET_GROWTH = 2.0 ** 0.25

_LOG_GROWTH = math.log(BUCKET_GROWTH)
_N_LOG_BUCKETS = int(math.ceil(math.log(BUCKET_HI / BUCKET_LO) / _LOG_GROWTH))

#: upper boundary of every bucket, in seconds, ascending; the overflow
#: bucket's boundary is +inf.  ``len(BUCKET_BOUNDS) == bucket count``.
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    [BUCKET_LO]
    + [BUCKET_LO * BUCKET_GROWTH ** (i + 1) for i in range(_N_LOG_BUCKETS)]
    + [math.inf]
)


def bucket_index(value: float) -> int:
    """Index of the bucket whose range contains ``value`` (seconds).
    Total: ``value <= BUCKET_BOUNDS[bucket_index(value)]`` always — NaN
    and negatives land in the underflow bucket rather than raising (a
    clock hiccup must not crash an observe on the serving path)."""
    if not value > BUCKET_LO:  # catches <=, NaN
        return 0
    # Overflow is "past the last FINITE boundary": the log schema's top
    # bucket may overshoot BUCKET_HI (ceil rounding), and the containment
    # invariant is stated against BUCKET_BOUNDS, not the nominal HI.
    if value > BUCKET_BOUNDS[-2]:
        return len(BUCKET_BOUNDS) - 1
    idx = 1 + int(math.log(value / BUCKET_LO) / _LOG_GROWTH)
    # float-edge guard: a value sitting exactly on a boundary can round
    # either side of the log; nudge into the bucket that contains it.
    if idx >= len(BUCKET_BOUNDS) - 1:
        idx = len(BUCKET_BOUNDS) - 2
    while idx > 0 and value <= BUCKET_BOUNDS[idx - 1]:
        idx -= 1
    while value > BUCKET_BOUNDS[idx]:
        idx += 1
    return idx


class LogBucketHistogram:
    """Counts over the shared ``BUCKET_BOUNDS`` schema, plus exact count
    and sum (the two moments Prometheus histograms carry).  Mergeable:
    ``merge`` adds counts bucket-wise — the rolling ring and the /prom
    exposition both build on that."""

    __slots__ = ("counts", "count", "sum")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * len(BUCKET_BOUNDS)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bucket_index(value)] += 1
        self.count += 1
        self.sum += value

    def clear(self) -> None:
        for i in range(len(self.counts)):
            self.counts[i] = 0
        self.count = 0
        self.sum = 0.0

    def merge(self, other: "LogBucketHistogram") -> "LogBucketHistogram":
        counts = self.counts
        for i, c in enumerate(other.counts):
            if c:
                counts[i] += c
        self.count += other.count
        self.sum += other.sum
        return self

    def quantile(self, q: float) -> float:
        """The ``q``-quantile (q in [0, 100]) in seconds, NaN when empty.
        Nearest-rank over the bucket counts; the returned value is the
        geometric midpoint of the bucket holding that rank (the overflow
        bucket reports its lower edge — its upper edge is infinite), so
        it always lies within one bucket width of the exact sample
        quantile."""
        if self.count == 0:
            return float("nan")
        rank = min(self.count - 1, int(q / 100.0 * self.count))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum > rank:
                return self._bucket_value(i)
        return self._bucket_value(len(self.counts) - 1)  # pragma: no cover

    @staticmethod
    def _bucket_value(idx: int) -> float:
        hi = BUCKET_BOUNDS[idx]
        if idx == 0:
            return hi / 2.0
        lo = BUCKET_BOUNDS[idx - 1]
        if math.isinf(hi):
            return lo
        return math.sqrt(lo * hi)

    def fraction_above(self, threshold: float) -> float:
        """Fraction of observations strictly above ``threshold`` seconds,
        to bucket precision (observations inside the threshold's own
        bucket count as NOT above — the conservative reading for an SLO
        breach signal: a breach is claimed only once it is provable from
        the bucket counts).  0.0 when empty."""
        if self.count == 0:
            return 0.0
        idx = bucket_index(threshold)
        above = sum(self.counts[idx + 1:])
        return above / self.count

    def snapshot(self) -> Dict[str, object]:
        """JSON-able export: boundaries (seconds), per-bucket counts,
        total count and sum — the shape ``runtime.promtext`` renders as a
        Prometheus histogram family."""
        return {"bounds": list(BUCKET_BOUNDS[:-1]),  # +Inf implied
                "counts": list(self.counts),
                "count": self.count,
                "sum": self.sum}


class RollingHistogram:
    """``slices`` time-sliced ``LogBucketHistogram``s covering a rolling
    ``window_s``-second horizon (class docstring above).  Not itself
    thread-safe: ``Metrics`` serializes access under its own lock, the
    same contract the old deque windows had."""

    def __init__(self, window_s: float = 120.0, slices: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0 or slices <= 0:
            raise ValueError("window_s and slices must be positive")
        self.window_s = float(window_s)
        self.slices = int(slices)
        self.slice_s = self.window_s / self.slices
        self._clock = clock
        self._hists = [LogBucketHistogram() for _ in range(self.slices)]
        #: epoch (slice number since clock 0) held by each ring position;
        #: -1 = never written.  A position whose epoch is older than
        #: ``current - slices + 1`` is expired: skipped on read, recycled
        #: on write.
        self._epochs = [-1] * self.slices

    def _epoch(self, now: Optional[float]) -> int:
        return int((self._clock() if now is None else now) / self.slice_s)

    def observe(self, value: float, now: Optional[float] = None) -> None:
        epoch = self._epoch(now)
        pos = epoch % self.slices
        if self._epochs[pos] != epoch:
            self._hists[pos].clear()
            self._epochs[pos] = epoch
        self._hists[pos].observe(value)  # ocvf-lint: disable=metrics-registry -- LogBucketHistogram.observe takes a sample VALUE (seconds), not a metric name; the registry rule pattern-matches the method name

    def merged(self, horizon_s: Optional[float] = None,
               now: Optional[float] = None) -> LogBucketHistogram:
        """One histogram over the slices still inside ``horizon_s``
        (default: the full window).  The current (partial) slice always
        counts; a horizon of k full slices therefore reads up to k+1
        slice epochs — the documented "within one slice" horizon
        granularity."""
        epoch = self._epoch(now)
        horizon = self.window_s if horizon_s is None else float(horizon_s)
        depth = min(self.slices, 1 + int(math.ceil(horizon / self.slice_s)))
        oldest = epoch - depth + 1
        out = LogBucketHistogram()
        for pos in range(self.slices):
            if oldest <= self._epochs[pos] <= epoch:
                out.merge(self._hists[pos])
        return out

    # convenience pass-throughs (each is one merge + one walk)

    def quantile(self, q: float, horizon_s: Optional[float] = None,
                 now: Optional[float] = None) -> float:
        return self.merged(horizon_s, now).quantile(q)

    def fraction_above(self, threshold: float,
                       horizon_s: Optional[float] = None,
                       now: Optional[float] = None) -> float:
        return self.merged(horizon_s, now).fraction_above(threshold)

    def count(self, horizon_s: Optional[float] = None,
              now: Optional[float] = None) -> int:
        return self.merged(horizon_s, now).count

    def clear(self) -> None:
        for hist in self._hists:
            hist.clear()
        for i in range(self.slices):
            self._epochs[i] = -1

    def memory_cells(self) -> int:
        """Total bucket cells held — a constant for a given construction,
        whatever was observed (the flat-memory soak assertion)."""
        return sum(len(h.counts) for h in self._hists)
