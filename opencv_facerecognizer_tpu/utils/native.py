"""ctypes bindings for the native C++ image loader (native/ocvf_loader.cpp).

The reference's host decode/resize path was native C++ via OpenCV
(SURVEY.md §2.2); this module is the rebuild's equivalent, covering the
uncompressed formats the classic face datasets use (PGM/PPM/BMP — ORL and
Yale-B are PGM). Anything else (JPEG/PNG) returns None here and
``utils.dataset`` falls back to PIL.

The shared library is compiled on demand with g++ (one time, cached next
to the source as ``native/libocvf_loader.so``); pybind11 is not available
in this environment, so the boundary is a flat ``extern "C"`` API over
preallocated numpy buffers — zero copies on the Python side.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO, "native", "ocvf_loader.cpp")
_SO = os.path.join(_REPO, "native", "libocvf_loader.so")

_lock = threading.Lock()
_lib_handle = None
_lib_failed = False


def _build() -> bool:
    # Compile to a private temp path and rename into place: a concurrent or
    # interrupted build must never leave a truncated .so at _SO (dlopen of a
    # half-written ELF would permanently disable the loader for readers, and
    # the mtime check would skip rebuilding it).
    tmp = f"{_SO}.build.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except Exception:  # ocvf-lint: disable=swallowed-exception -- optional-acceleration probe: no compiler / failed build means the pure-NumPy path serves, and False is the recorded verdict
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib_handle, _lib_failed
    if _lib_handle is not None or _lib_failed:
        return _lib_handle
    with _lock:
        if _lib_handle is not None or _lib_failed:
            return _lib_handle
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not (os.path.exists(_SRC) and _build()):
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.ocvf_probe.restype = ctypes.c_int
            lib.ocvf_probe.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ]
            lib.ocvf_decode_gray.restype = ctypes.c_int
            lib.ocvf_decode_gray.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
            ]
            lib.ocvf_load_gray.restype = ctypes.c_int
            lib.ocvf_load_gray.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
            ]
            lib.ocvf_load_batch.restype = ctypes.c_int
            lib.ocvf_load_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int),
            ]
            _lib_handle = lib
        except OSError:
            _lib_failed = True
    return _lib_handle


def available() -> bool:
    return _lib() is not None


_MAGIC = (b"P2", b"P3", b"P5", b"P6", b"BM")


def handles(path_or_bytes) -> bool:
    """Cheap magic-byte check: is this a format the native loader decodes?"""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        head = bytes(path_or_bytes[:2])
    else:
        try:
            with open(path_or_bytes, "rb") as f:
                head = f.read(2)
        except OSError:
            return False
    return head in _MAGIC


def decode_gray(
    data: bytes, size: Optional[Tuple[int, int]] = None
) -> Optional[np.ndarray]:
    """Decode PGM/PPM/BMP bytes -> float32 [H, W] (0..255), optionally
    resized to ``size=(H, W)``. None when unsupported/undecodable."""
    lib = _lib()
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(data, len(data))
    if size is None:
        h, w = ctypes.c_int(), ctypes.c_int()
        if lib.ocvf_probe(ctypes.cast(buf, ctypes.c_char_p), len(data),
                          ctypes.byref(h), ctypes.byref(w)) != 0:
            return None
        oh, ow = h.value, w.value
    else:
        oh, ow = int(size[0]), int(size[1])
    out = np.empty((oh, ow), np.float32)
    rc = lib.ocvf_decode_gray(
        ctypes.cast(buf, ctypes.c_char_p), len(data), oh, ow,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out if rc == 0 else None


def load_gray(
    path: str, size: Optional[Tuple[int, int]] = None
) -> Optional[np.ndarray]:
    """Load + decode + resize one file; None on any failure (caller falls
    back to PIL)."""
    lib = _lib()
    if lib is None or not handles(path):
        return None
    if size is None:
        try:
            with open(path, "rb") as f:
                return decode_gray(f.read(), None)
        except OSError:
            return None
    out = np.empty((int(size[0]), int(size[1])), np.float32)
    rc = lib.ocvf_load_gray(
        path.encode(), int(size[0]), int(size[1]),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out if rc == 0 else None


def load_batch(
    paths: List[str], size: Tuple[int, int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack many files into one [N, H, W] float32 batch in native code.

    Returns (batch, ok_mask); rows with ok_mask False were undecodable (the
    caller decides whether to PIL-fallback or skip them).
    """
    lib = _lib()
    n = len(paths)
    oh, ow = int(size[0]), int(size[1])
    out = np.zeros((n, oh, ow), np.float32)
    if lib is None or n == 0:
        return out, np.zeros((n,), bool)
    arr = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    status = np.empty((n,), np.int32)
    lib.ocvf_load_batch(
        arr, n, oh, ow,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
    )
    return out, status == 0
