"""The chained-differencing timing instrument, shared by every benchmark
(bench.py, scripts/bench_lifecycle.py, scripts/explore_perf.py) so the
artifacts cannot silently diverge in methodology.

Why this exists (measured on the axon tunneled PJRT backend, see bench.py's
module docstring for the full analysis): ``block_until_ready`` does not
await execution there, and any blocking readback quantizes at a ~100 ms
sync-poll interval — so per-iteration wall timing is fiction. Instead, K
iterations are serialized INSIDE one jit via a 1e-30-scaled data dependency
and the whole chain is timed with a single readback; the per-iteration cost
is the difference of two chain lengths' minima:

    (min T(K2) - min T(K1)) / (K2 - K1)

Jitter only ever ADDS to a single chain's wall time, so min-of-repeats per
length is taken BEFORE differencing (min-ing individual pair diffs is
biased low). K2 escalates up a ladder until the delta clears the readback
quantization. The method reproduces 218 TFLOP/s on a bare 4096^3 bf16
matmul (nominal peak 197) — calibration within instrument error.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

CHAIN_K1 = 4
#: Escalation ladder: the chain delta must dwarf the backend's ~100 ms
#: readback quantization; fast configs need the long chains. The top rung
#: sets the resolution floor: MIN_DELTA_S / (8192 - 4) ~= 31 us/iter —
#: below every per-stage cost this framework measures (the cheapest, the
#: detector forward at 0.199 ms/batch, needs k2 >= ~1260 to clear 0.25 s).
CHAIN_K2_LADDER = (34, 154, 1024, 8192)
MIN_DELTA_S = 0.25
MEASURE_PAIRS = 3


def measure_chained(
    run_chain: Callable[[int], float],
    *,
    k1: int = CHAIN_K1,
    k2_ladder: Sequence[int] = CHAIN_K2_LADDER,
    min_delta_s: float = MIN_DELTA_S,
    pairs: int = MEASURE_PAIRS,
) -> Tuple[list, list, int, Optional[float]]:
    """min-of-chains differencing with K2 escalation.

    ``run_chain(k)`` must execute the k-length chain end-to-end (warm
    compile included on its first call per k) and return the wall seconds
    of ONE timed run. Returns (t_k1_samples, t_k2_samples, k2_used,
    seconds_per_iteration_or_None).
    """
    t1s = [run_chain(k1) for _ in range(pairs)]
    t2s, k2, delta = [], k2_ladder[0], 0.0
    resolved = False
    for k2 in k2_ladder:
        t2s = [run_chain(k2) for _ in range(pairs)]
        delta = min(t2s) - min(t1s)
        if delta >= min_delta_s:
            resolved = True
            break
    if not resolved:
        # Ladder exhausted without the delta ever clearing the readback
        # quantization: the measurement is under-resolved, not merely fast.
        # Reporting it as a valid per-iteration time would launder ~100 ms
        # readback noise into the artifacts.
        return t1s, t2s, k2, None
    per_iter = delta / (k2 - k1)
    return t1s, t2s, k2, (per_iter if per_iter > 1e-6 else None)


def scalar_chain_ms(
    scalar_fn: Callable[..., "object"],
    args: tuple,
    **kwargs,
) -> Optional[float]:
    """ms/iteration of ``scalar_fn(*args) -> f32 scalar`` via the chained
    instrument. The LAST element of ``args`` must be the array the
    dependency threads through (iteration i sees ``args[-1] + dep``)."""
    import jax
    import jax.numpy as jnp

    def chained(k, *a):
        def body(i, carry):
            dep, acc = carry
            out = scalar_fn(*a[:-1], a[-1] + dep)
            dep = out * 1e-30
            return dep, acc + out

        return jax.lax.fori_loop(0, k, body,
                                 (jnp.float32(0.0), jnp.float32(0.0)))[1]

    jc = jax.jit(chained, static_argnums=0)

    def run_chain(k):
        _ = np.asarray(jc(k, *args))  # warm: compile this k
        t0 = time.perf_counter()
        _ = np.asarray(jc(k, *args))  # one readback forces the whole chain
        return time.perf_counter() - t0

    *_rest, per_iter = measure_chained(run_chain, **kwargs)
    return None if per_iter is None else per_iter * 1e3
