"""Cross-cutting utilities (SURVEY.md §1 L5): datasets, validation,
serialization, metrics."""

from opencv_facerecognizer_tpu.utils import dataset, serialization, validation

__all__ = ["dataset", "serialization", "validation"]
