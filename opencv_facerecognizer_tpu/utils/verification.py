"""Face-verification evaluation: the LFW 6000-pair protocol machinery
(BASELINE.json:11 "FaceNet/ArcFace CNN embedding backend, LFW 6000-pair
verification"; SURVEY.md §6).

The real LFW images are unreachable in this zero-egress environment
(SURVEY.md §0), so the protocol is implemented dataset-agnostically:
``make_verification_pairs`` builds a balanced same/different pair list from
any labeled dataset, and ``verification_accuracy`` runs the standard
10-fold threshold-selection protocol (threshold chosen on 9 folds, applied
to the held-out fold) over cosine similarity.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def make_verification_pairs(
    labels: np.ndarray, num_pairs: int = 6000, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Balanced (idx_a, idx_b, is_same) arrays, LFW-style: half genuine
    pairs, half impostor pairs, no self-pairs."""
    rng = np.random.default_rng(seed)
    labels = np.asarray(labels)
    by_class = {c: np.flatnonzero(labels == c) for c in np.unique(labels)}
    multi = [c for c, idx in by_class.items() if len(idx) >= 2]
    classes = list(by_class)
    if len(multi) == 0 or len(classes) < 2:
        raise ValueError("need >=1 class with >=2 samples and >=2 classes")
    half = num_pairs // 2
    a, b, same = [], [], []
    for _ in range(half):
        c = multi[rng.integers(len(multi))]
        i, j = rng.choice(by_class[c], size=2, replace=False)
        a.append(i), b.append(j), same.append(True)
    for _ in range(num_pairs - half):
        c1, c2 = rng.choice(len(classes), size=2, replace=False)
        i = rng.choice(by_class[classes[c1]])
        j = rng.choice(by_class[classes[c2]])
        a.append(i), b.append(j), same.append(False)
    return np.asarray(a), np.asarray(b), np.asarray(same)


def cosine_similarity(e1: np.ndarray, e2: np.ndarray) -> np.ndarray:
    e1 = e1 / np.maximum(np.linalg.norm(e1, axis=-1, keepdims=True), 1e-12)
    e2 = e2 / np.maximum(np.linalg.norm(e2, axis=-1, keepdims=True), 1e-12)
    return np.sum(e1 * e2, axis=-1)


def _best_threshold(similarities: np.ndarray, is_same: np.ndarray) -> float:
    order = np.argsort(similarities)
    s_sorted = similarities[order]
    y_sorted = is_same[order].astype(np.int64)
    # For threshold between s[i-1] and s[i]: predictions below are "diff".
    # accuracy(i) = (#diff in [0,i)) + (#same in [i,n)).
    diff_below = np.concatenate([[0], np.cumsum(1 - y_sorted)])
    same_at_or_above = y_sorted.sum() - np.concatenate([[0], np.cumsum(y_sorted)])
    correct = diff_below + same_at_or_above
    i = int(np.argmax(correct))
    if i == 0:
        return float(s_sorted[0] - 1e-6)
    if i == len(s_sorted):
        return float(s_sorted[-1] + 1e-6)
    return float((s_sorted[i - 1] + s_sorted[i]) / 2)


def verification_accuracy(
    emb_a: np.ndarray, emb_b: np.ndarray, is_same: np.ndarray, folds: int = 10,
    return_folds: bool = False,
):
    """10-fold LFW protocol: per fold, pick the accuracy-optimal cosine
    threshold on the other folds, evaluate on the held-out fold.

    Returns (mean_accuracy, std_accuracy, mean_threshold), plus the
    per-fold accuracy list when ``return_folds`` — recorded so callers
    can gate on the fold MINIMUM, not just the mean (a spread whose
    lower edge sits on the bar is not "beating" it).
    """
    sims = cosine_similarity(np.asarray(emb_a), np.asarray(emb_b))
    is_same = np.asarray(is_same, dtype=bool)
    n = len(sims)
    idx = np.arange(n)
    fold_ids = idx % folds
    accs, thresholds = [], []
    for f in range(folds):
        test = fold_ids == f
        train = ~test
        t = _best_threshold(sims[train], is_same[train])
        pred = sims[test] >= t
        accs.append(float(np.mean(pred == is_same[test])))
        thresholds.append(t)
    out = (float(np.mean(accs)), float(np.std(accs)),
           float(np.mean(thresholds)))
    return (*out, accs) if return_folds else out
