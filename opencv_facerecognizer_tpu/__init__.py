"""opencv_facerecognizer_tpu — a TPU-native face recognition framework.

A ground-up JAX/XLA rebuild of the capabilities of
``sandykindy/opencv_facerecognizer`` (the OCVFACEREC / bytefish-facerec
lineage; see SURVEY.md for the structural blueprint — the reference mount was
empty at build time, so citations are to SURVEY.md sections instead of
reference file:line).

Layering (mirrors SURVEY.md §1, rebuilt TPU-first):

- ``ops``      — pure jittable device math: distances, LBP codes, image ops,
                 PCA/LDA eigen-solvers, spatial histograms.
- ``models``   — the plugin boundary the reference's north star preserves
                 (SURVEY.md §1 L2-L4): ``AbstractFeature.compute/extract``,
                 ``AbstractClassifier.compute/predict``, ``PredictableModel``.
- ``utils``    — datasets, validation, serialization (pickle-free), metrics.

Further layers follow the SURVEY.md §7 build order as they land: CNN
embedder/detector under ``models``, device-mesh sharding under ``parallel``,
and the serving runtime (batcher/connectors/trainer) under ``runtime``.
"""

__version__ = "0.1.0"
