// Native host-side image loader for opencv_facerecognizer_tpu.
//
// The reference's host decode path was native C++ (OpenCV's imread/resize —
// SURVEY.md §2.2 "cv2.resize, cv2.cvtColor, image decode"). This is the
// rebuild's native equivalent for the formats the classic face datasets
// actually use (ORL/AT&T and Yale-B ship PGM; PPM/BMP cover the other
// uncompressed cases): decode -> grayscale luminance -> fused bilinear
// resize straight into a caller-provided float32 buffer, so read_images can
// pack a training batch without any intermediate Python objects. JPEG/PNG
// fall back to PIL in utils/native.py (libjpeg/libpng linkage isn't worth
// it when the fallback already covers them).
//
// Build: g++ -O3 -shared -fPIC -o libocvf_loader.so ocvf_loader.cpp
// (utils/native.py does this on demand and caches the .so).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr int kErrRead = -1;
constexpr int kErrFormat = -2;
constexpr int kErrBounds = -3;

struct GrayImage {
  int h = 0;
  int w = 0;
  std::vector<float> px;  // luminance, [0, 255]
};

// ---- PNM (P2/P3/P5/P6) ----

bool pnm_token(const uint8_t* d, int64_t n, int64_t& pos, long& out) {
  // Skip whitespace and '#' comments, then parse one non-negative integer.
  while (pos < n) {
    uint8_t c = d[pos];
    if (c == '#') {
      while (pos < n && d[pos] != '\n') pos++;
    } else if (c == ' ' || c == '\t' || c == '\r' || c == '\n') {
      pos++;
    } else {
      break;
    }
  }
  if (pos >= n || d[pos] < '0' || d[pos] > '9') return false;
  long v = 0;
  while (pos < n && d[pos] >= '0' && d[pos] <= '9') {
    v = v * 10 + (d[pos] - '0');
    pos++;
  }
  out = v;
  return true;
}

int decode_pnm(const uint8_t* d, int64_t n, GrayImage& img) {
  if (n < 2 || d[0] != 'P') return kErrFormat;
  int kind = d[1] - '0';
  if (kind != 2 && kind != 3 && kind != 5 && kind != 6) return kErrFormat;
  bool color = (kind == 3 || kind == 6);
  bool ascii = (kind == 2 || kind == 3);
  int64_t pos = 2;
  long w, h, maxval;
  if (!pnm_token(d, n, pos, w) || !pnm_token(d, n, pos, h) ||
      !pnm_token(d, n, pos, maxval))
    return kErrFormat;
  if (w <= 0 || h <= 0 || w > 1 << 16 || h > 1 << 16 || maxval <= 0 ||
      maxval > 65535)
    return kErrFormat;
  double scale = 255.0 / (double)maxval;
  int64_t count = (int64_t)h * w * (color ? 3 : 1);
  // Bounds-check BEFORE allocating h*w pixels: a crafted header like
  // "P5 60000 60000" over a 1-byte body must fail here, not in a 14 GB
  // px.resize (std::bad_alloc aborts the process across the ctypes
  // boundary). ASCII needs >= 2 bytes (digit + separator) per value.
  int64_t min_body = ascii ? 2 * count - 1 : count * (maxval > 255 ? 2 : 1);
  if (pos + min_body > n) return kErrBounds;
  img.h = (int)h;
  img.w = (int)w;
  img.px.resize((size_t)h * w);

  if (ascii) {
    std::vector<long> vals((size_t)count);
    for (int64_t i = 0; i < count; i++) {
      if (!pnm_token(d, n, pos, vals[(size_t)i])) return kErrBounds;
    }
    for (int64_t i = 0; i < (int64_t)h * w; i++) {
      double v = color ? 0.299 * vals[(size_t)(3 * i)] +
                             0.587 * vals[(size_t)(3 * i + 1)] +
                             0.114 * vals[(size_t)(3 * i + 2)]
                       : (double)vals[(size_t)i];
      img.px[(size_t)i] = (float)(v * scale);
    }
    return 0;
  }

  pos += 1;  // exactly one whitespace byte after maxval in binary PNM
  int bytes_per = maxval > 255 ? 2 : 1;
  if (pos + count * bytes_per > n) return kErrBounds;
  const uint8_t* p = d + pos;
  for (int64_t i = 0; i < (int64_t)h * w; i++) {
    double c0, c1, c2;
    if (bytes_per == 1) {
      if (color) {
        c0 = p[3 * i]; c1 = p[3 * i + 1]; c2 = p[3 * i + 2];
      } else {
        c0 = c1 = c2 = p[i];
      }
    } else {  // 16-bit PNM is big-endian
      auto rd = [&](int64_t j) { return (double)((p[2 * j] << 8) | p[2 * j + 1]); };
      if (color) {
        c0 = rd(3 * i); c1 = rd(3 * i + 1); c2 = rd(3 * i + 2);
      } else {
        c0 = c1 = c2 = rd(i);
      }
    }
    double v = color ? 0.299 * c0 + 0.587 * c1 + 0.114 * c2 : c0;
    img.px[(size_t)i] = (float)(v * scale);
  }
  return 0;
}

// ---- BMP (uncompressed 8/24/32-bit) ----

uint32_t le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}
uint16_t le16(const uint8_t* p) { return (uint16_t)(p[0] | (p[1] << 8)); }

int decode_bmp(const uint8_t* d, int64_t n, GrayImage& img) {
  if (n < 54 || d[0] != 'B' || d[1] != 'M') return kErrFormat;
  uint32_t data_off = le32(d + 10);
  uint32_t hdr_size = le32(d + 14);
  if (hdr_size < 40) return kErrFormat;
  int32_t w = (int32_t)le32(d + 18);
  int32_t h = (int32_t)le32(d + 22);
  uint16_t bpp = le16(d + 28);
  uint32_t compression = le32(d + 30);
  bool bottom_up = h > 0;
  int32_t ah = bottom_up ? h : -h;
  if (w <= 0 || ah <= 0 || w > 1 << 16 || ah > 1 << 16) return kErrFormat;
  if (compression != 0 || (bpp != 8 && bpp != 24 && bpp != 32))
    return kErrFormat;

  const uint8_t* palette = nullptr;
  uint32_t pal_colors = 256;
  if (bpp == 8) {
    uint32_t colors = le32(d + 46);
    if (colors == 0 || colors > 256) colors = 256;
    // int64 arithmetic: uint32 sums here can wrap on crafted headers and
    // pass the check, leaving the pixel loop reading past the buffer.
    int64_t pal_off = 14 + (int64_t)hdr_size;
    int64_t pal_end = pal_off + 4 * (int64_t)colors;
    if (pal_end > (int64_t)data_off || pal_end > n) return kErrFormat;
    palette = d + pal_off;  // BGRA quads
    pal_colors = colors;    // pixel indices are clamped to this below
  }
  int64_t row_bytes = (((int64_t)w * bpp + 31) / 32) * 4;
  if ((int64_t)data_off + row_bytes * ah > n) return kErrBounds;

  img.h = ah;
  img.w = w;
  img.px.resize((size_t)ah * w);
  for (int32_t y = 0; y < ah; y++) {
    const uint8_t* row = d + data_off + row_bytes * (bottom_up ? ah - 1 - y : y);
    for (int32_t x = 0; x < w; x++) {
      double b, g, r;
      if (bpp == 8) {
        uint32_t ci = row[x];
        if (ci >= pal_colors) ci = pal_colors - 1;  // corrupt pixel index
        const uint8_t* q = palette + 4 * ci;
        b = q[0]; g = q[1]; r = q[2];
      } else {
        const uint8_t* q = row + (bpp / 8) * x;
        b = q[0]; g = q[1]; r = q[2];
      }
      img.px[(size_t)y * w + x] = (float)(0.299 * r + 0.587 * g + 0.114 * b);
    }
  }
  return 0;
}

int decode_any(const uint8_t* d, int64_t n, GrayImage& img) {
  if (n >= 2 && d[0] == 'P' && d[1] >= '2' && d[1] <= '6')
    return decode_pnm(d, n, img);
  if (n >= 2 && d[0] == 'B' && d[1] == 'M') return decode_bmp(d, n, img);
  return kErrFormat;
}

// Bilinear resize (align_corners=false, the cv2/PIL convention) into out.
void resize_bilinear(const GrayImage& img, int oh, int ow, float* out) {
  if (oh == img.h && ow == img.w) {
    memcpy(out, img.px.data(), sizeof(float) * (size_t)oh * ow);
    return;
  }
  double sy = (double)img.h / oh, sx = (double)img.w / ow;
  for (int y = 0; y < oh; y++) {
    double fy = (y + 0.5) * sy - 0.5;
    int y0 = (int)fy;
    if (fy < 0) { fy = 0; y0 = 0; }
    int y1 = y0 + 1 < img.h ? y0 + 1 : img.h - 1;
    double wy = fy - y0;
    for (int x = 0; x < ow; x++) {
      double fx = (x + 0.5) * sx - 0.5;
      int x0 = (int)fx;
      if (fx < 0) { fx = 0; x0 = 0; }
      int x1 = x0 + 1 < img.w ? x0 + 1 : img.w - 1;
      double wx = fx - x0;
      const float* p = img.px.data();
      double top = p[(size_t)y0 * img.w + x0] * (1 - wx) +
                   p[(size_t)y0 * img.w + x1] * wx;
      double bot = p[(size_t)y1 * img.w + x0] * (1 - wx) +
                   p[(size_t)y1 * img.w + x1] * wx;
      out[(size_t)y * ow + x] = (float)(top * (1 - wy) + bot * wy);
    }
  }
}

int load_file(const char* path, std::vector<uint8_t>& buf) {
  FILE* f = fopen(path, "rb");
  if (!f) return kErrRead;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (sz <= 0) { fclose(f); return kErrRead; }
  buf.resize((size_t)sz);
  size_t got = fread(buf.data(), 1, (size_t)sz, f);
  fclose(f);
  return got == (size_t)sz ? 0 : kErrRead;
}

}  // namespace

extern "C" {

// Probe dims without decoding pixels. Returns 0 and fills h/w on success.
int ocvf_probe(const uint8_t* data, int64_t len, int* h, int* w) {
  GrayImage img;
  int rc = decode_any(data, len, img);  // simple formats: decode IS cheap
  if (rc != 0) return rc;
  *h = img.h;
  *w = img.w;
  return 0;
}

// Decode + grayscale + resize to [out_h, out_w] float32 (0..255 range).
// out_h/out_w <= 0 means native size — caller must have probed.
int ocvf_decode_gray(const uint8_t* data, int64_t len, int out_h, int out_w,
                     float* out) {
  GrayImage img;
  int rc = decode_any(data, len, img);
  if (rc != 0) return rc;
  if (out_h <= 0 || out_w <= 0) {
    out_h = img.h;
    out_w = img.w;
  }
  resize_bilinear(img, out_h, out_w, out);
  return 0;
}

// File variant.
int ocvf_load_gray(const char* path, int out_h, int out_w, float* out) {
  std::vector<uint8_t> buf;
  int rc = load_file(path, buf);
  if (rc != 0) return rc;
  return ocvf_decode_gray(buf.data(), (int64_t)buf.size(), out_h, out_w, out);
}

// Pack a batch of files into one [count, out_h, out_w] float32 buffer.
// status[i] receives the per-file return code; returns number decoded OK.
int ocvf_load_batch(const char* const* paths, int count, int out_h, int out_w,
                    float* out, int* status) {
  int ok = 0;
  for (int i = 0; i < count; i++) {
    status[i] = ocvf_load_gray(paths[i], out_h, out_w,
                               out + (size_t)i * out_h * out_w);
    if (status[i] == 0) ok++;
  }
  return ok;
}

}  // extern "C"
